"""Always-on, low-overhead training telemetry (ISSUE 2 tentpole).

The profiler (``profiler.py``) answers "what happened inside this trace
session"; the monitor answers "is the job healthy *right now*" — in
production, with no profiler attached, at near-zero cost when disabled:

* a process-global **metrics registry** (`Counter`/`Gauge`/`Histogram`,
  ``registry()``) that every subsystem publishes into;
* **StepStats** — Executor/ParallelExecutor feed one record per
  ``run()`` (step wall time, examples/sec, fetch-sync wait,
  retrace/compile counts + cache hit ratio, dispatch-queue depth,
  prefetcher occupancy, device memory when the backend reports it);
* **exporters** — a rotating JSONL event log (``FLAGS_monitor_log_dir``),
  Prometheus-style text exposition (``expose_text()`` + an optional
  stdlib HTTP endpoint on ``FLAGS_monitor_port``), and a periodic
  console reporter (``FLAGS_monitor_console_seconds``);
* a **Watchdog** that heartbeats from the dispatch/prefetch worker
  threads and flags a hung pipeline (no step completed within
  ``FLAGS_monitor_stall_seconds``) with a diagnostic dump of queue
  states and the last completed span, instead of a silent hang.

Enablement is flag-driven: setting any of ``FLAGS_monitor``,
``FLAGS_monitor_log_dir``, or ``FLAGS_monitor_port`` turns the monitor
on (``monitor.enable()``/``disable()`` are set_flags conveniences).
Profiler spans double-publish into ``span/<name>`` histograms whenever
the monitor is on — with or without a profiler session — so the two
observability layers agree on what they both measure.
"""

import os
import sys
import threading
import time
import weakref

from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       DEFAULT_BUCKETS)
from .step_stats import StepStatsAggregator
from .exporters import JsonlWriter, ConsoleReporter, start_http_server
from .watchdog import Watchdog

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "DEFAULT_BUCKETS",
    "StepStatsAggregator", "JsonlWriter", "ConsoleReporter",
    "start_http_server", "Watchdog",
    "enable", "disable", "enabled", "registry", "step_stats",
    "expose_text", "record_step", "observe_span", "mark", "heartbeat",
    "last_span", "queue_states", "track", "log_event", "count", "run_id",
    "sample_device_gauges", "add_stall_listener", "remove_stall_listener",
    "goodput_ledger", "goodput_summary", "goodput_stamp",
    "goodput_reset", "tracing", "aggregate", "alerts", "health",
]

# fast-path gate: a module-global bool read (no lock, no flag lookup) is
# all a disabled process pays per instrumentation site
_enabled = False

# per-run correlation id: every JSONL record, step record, chrome-trace
# export, and /metrics exposition carries it, so the three views of one
# run can be joined after the fact (Dapper-style: one id, many sinks)
_RUN_ID = "%08x-%04x" % (int(time.time()) & 0xffffffff,
                         os.getpid() & 0xffff)


def run_id():
    """The process's run correlation id (stable for the process life)."""
    return _RUN_ID

_mu = threading.RLock()
_registry = MetricsRegistry()
_aggregator = StepStatsAggregator(_registry)
# goodput ledger: exclusive wall-clock attribution over the span/step/
# event streams (see goodput.py); fed only while the monitor is on
from .goodput import GoodputLedger  # noqa: E402  (needs nothing above)

_goodput = GoodputLedger(_registry)
_jsonl = None
_http = None
_console = None
_watchdog = None
_last_span = None                # (name, wall ts, duration seconds)
_span_totals = {}                # span name -> cumulative seconds
_last_fetch_sync = {}            # executor name -> fetch_sync total at
                                 # its previous record_step
# live pipeline components (AsyncDispatchQueue / DevicePrefetcher)
# self-register here; weak so the monitor never extends their lifetime
_tracked = weakref.WeakSet()
# config currently applied, so flag flips reconfigure only what changed
_applied = {}


def _flag(name, default):
    """Defensive flag read: during import-time env overrides the monitor
    flags register one at a time, so a sibling may not exist yet."""
    from .. import flags

    try:
        return flags.flag(name)
    except KeyError:
        return default


def _config():
    return {
        "on": bool(_flag("monitor", False))
        or bool(_flag("monitor_log_dir", ""))
        or int(_flag("monitor_port", 0)) > 0
        or float(_flag("monitor_console_seconds", 0.0)) > 0,
        "log_dir": _flag("monitor_log_dir", ""),
        "port": int(_flag("monitor_port", 0)),
        "stall_seconds": float(_flag("monitor_stall_seconds", 120.0)),
        "console_seconds": float(_flag("monitor_console_seconds", 0.0)),
    }


def _reconcile():
    """Bring the running components in line with the monitor flags.
    Called from every FLAGS_monitor* on_set hook."""
    global _enabled, _jsonl, _http, _console, _watchdog
    with _mu:
        cfg = _config()
        if _applied and all(_applied.get(k) == v for k, v in cfg.items()):
            return
        on = cfg["on"]
        # JSONL log
        fresh_jsonl = False
        if (cfg["log_dir"] if on else "") != _applied.get("_jsonl_dir", ""):
            if _jsonl is not None:
                _jsonl.close()
                _jsonl = None
            if on and cfg["log_dir"]:
                _jsonl = JsonlWriter(cfg["log_dir"])
                fresh_jsonl = True
            _applied["_jsonl_dir"] = cfg["log_dir"] if on else ""
        # HTTP exposition endpoint
        want_port = cfg["port"] if on else 0
        if want_port != _applied.get("_http_port", 0):
            if _http is not None:
                _http.shutdown()
                _http.server_close()   # shutdown() alone leaks the fd
                _http = None
            if want_port > 0:
                try:
                    _http = start_http_server(want_port, expose_text)
                except OSError as e:
                    # EADDRINUSE etc.: an exporter that can't bind must
                    # not abort set_flags mid-family and leave a
                    # half-applied config — warn and run without it
                    print("[monitor] /metrics endpoint disabled: %r" % e,
                          file=sys.stderr, flush=True)
            _applied["_http_port"] = want_port
        # watchdog
        want_stall = cfg["stall_seconds"] if on else 0.0
        if want_stall != _applied.get("_stall", 0.0):
            if _watchdog is not None:
                _watchdog.stop()
                _watchdog = None
            if want_stall > 0:
                _watchdog = Watchdog(want_stall, sink=_stall_sink,
                                     probe=_stall_probe).start()
            _applied["_stall"] = want_stall
        # console reporter
        want_console = cfg["console_seconds"] if on else 0.0
        if want_console != _applied.get("_console", 0.0):
            if _console is not None:
                _console.stop()
                _console = None
            if want_console > 0:
                _console = ConsoleReporter(
                    _aggregator, _registry,
                    interval_s=want_console).start()
            _applied["_console"] = want_console
        _applied.update(cfg)
        newly_on = on and not _enabled
        if on != _enabled:
            # enable/disable boundaries drop the cached metric handles:
            # tests (and operators) reset the registry while disabled,
            # and a stale handle would observe into an orphaned metric
            _span_hists.clear()
            _prog_metrics.clear()
            _dev_metrics.clear()
            _aggregator.reset()
            # attribution restarts with the session: a re-enabled
            # monitor must not book the disabled stretch as idle
            _goodput.reset()
            # per-program step accounting (and the watchdog's suspect-
            # program pointer) restarts with the session; captured
            # profiles are compile artifacts and survive
            _last_fp[0] = None
            program_profile.reset_accounting()
        _enabled = on
        if newly_on or (on and fresh_jsonl):
            # set_flags applies the flag family one at a time, so the
            # writer may appear a beat after the enable flip — log the
            # lifecycle event whenever a fresh log gets its first chance
            log_event({"event": "monitor_enabled", "ts": time.time(),
                       "config": {k: v for k, v in cfg.items()
                                  if k != "on"}})


def enabled():
    return _enabled


def enable(log_dir=None, port=None, stall_seconds=None,
           console_seconds=None):
    """Turn monitoring on (optionally configuring the exporters) — a
    convenience over ``set_flags``; flags stay the source of truth."""
    from .. import flags

    updates = {"monitor": True}
    if log_dir is not None:
        updates["monitor_log_dir"] = log_dir
    if port is not None:
        updates["monitor_port"] = port
    if stall_seconds is not None:
        updates["monitor_stall_seconds"] = stall_seconds
    if console_seconds is not None:
        updates["monitor_console_seconds"] = console_seconds
    flags.set_flags(updates)


def disable():
    """Turn monitoring fully off: resets every FLAGS_monitor* flag to
    its default and stops the exporters/watchdog.  Collected metrics
    are kept (``registry().reset()`` drops them)."""
    from .. import flags

    flags.set_flags({"monitor": False, "monitor_log_dir": "",
                     "monitor_port": 0, "monitor_stall_seconds": 120.0,
                     "monitor_console_seconds": 0.0})


def registry():
    """The process-global metrics registry."""
    return _registry


def step_stats():
    """The process-global StepStats aggregator."""
    return _aggregator


def goodput_ledger():
    """The process-global goodput ledger (exclusive wall-clock
    attribution; see ``monitor/goodput.py``).  The submodule itself
    stays reachable as ``monitor.goodput`` (classifier table)."""
    return _goodput


def goodput_summary():
    """The per-run attribution summary: bucket seconds, total wall,
    goodput ratio — the live twin of ``tools/goodput_report.py``."""
    return _goodput.summary()


def goodput_stamp():
    """Log the current attribution summary as a ``goodput`` JSONL
    record (run boundaries: bench rung ends, Trainer.train exit) and
    return it."""
    summ = _goodput.summary()
    if _enabled:
        log_event(dict(summ, event="goodput", ts=time.time()))
    return summ


def goodput_reset():
    """Restart the attribution window (bench rungs call this next to
    ``step_stats().reset()`` so each rung's artifact carries its own
    attribution)."""
    _goodput.reset()


def expose_text():
    """Prometheus text exposition of every registered metric.  The
    leading comment carries the run correlation id, so a scraped
    /metrics snapshot can be joined against the JSONL log and chrome
    traces of the same run."""
    return "# run_id %s\n" % _RUN_ID + _registry.expose_text()


def track(component):
    """Register a pipeline component exposing ``monitor_state()`` (the
    dispatch queues and prefetchers self-register) for watchdog dumps
    and StepStats occupancy; weakly held."""
    _tracked.add(component)


def queue_states():
    """``monitor_state()`` of every live tracked component."""
    out = []
    try:
        # snapshot first: the watchdog thread reads while training
        # threads construct executors/prefetchers (WeakSet.add)
        comps = list(_tracked)
    except RuntimeError:       # set mutated mid-iteration; retry once
        comps = list(_tracked)
    for c in comps:
        try:
            out.append(c.monitor_state())
        except Exception as e:  # noqa: BLE001 — diagnostics must land
            out.append({"kind": type(c).__name__, "error": repr(e)})
    return out


def last_span():
    """(name, wall-clock ts, seconds) of the last completed profiler
    span double-published into the monitor, or None."""
    return _last_span


def log_event(record):
    """Write one record to the JSONL event log (no-op when unset).
    Every record is stamped with the run correlation id.  Enabled
    processes also tee the record into the goodput ledger, which is how
    checkpoint/rollback/stall events reach the attribution without the
    producers knowing about it."""
    if _enabled:
        try:
            _goodput.note_event(record)
        except Exception:  # noqa: BLE001 — telemetry never breaks a step
            pass
    j = _jsonl
    if j is not None:
        record.setdefault("run_id", _RUN_ID)
        j.write(record)


def count(name, amount=1):
    """Increment a counter iff the monitor is on — the one shared
    enabled-gated increment for decision-trail counters (guardian,
    fault harness, master reconnects), so the disabled-is-free
    contract lives in one place."""
    if _enabled:
        _registry.counter(name).inc(amount)


# ---------------------------------------------------------------------------
# instrumentation entry points (called from executor/reader/profiler)
# ---------------------------------------------------------------------------

# span histogram handles cached by name: the registry's get-or-create
# lock (and the bucket-equality check) happen once per distinct span
# name, not once per span.  _span_gen tracks the registry generation so
# a registry.reset() (tests) orphans no cached handle.
_span_hists = {}
_span_gen = [0]


def _refresh_handle_caches():
    """Drop every cached metric handle iff the registry generation moved
    (a registry.reset() orphaned them).  One shared latch for all three
    handle caches: whichever cache notices the reset first must drop
    them all, or a sibling would keep serving orphaned handles."""
    if _span_gen[0] != _registry.generation:
        _span_hists.clear()
        _prog_metrics.clear()
        _dev_metrics.clear()
        _span_gen[0] = _registry.generation


def observe_span(name, dur_us, args=None):
    """Double-publish a completed profiler span into the monitor:
    ``span/<name>`` histogram (seconds) + cumulative totals (feeds the
    StepStats fetch-sync wait and the watchdog's last-span field) + the
    goodput ledger's span classifier (``args`` may carry the producer's
    explicit ``bucket`` hint)."""
    global _last_span
    if not _enabled:
        return
    dur_s = dur_us / 1e6
    _refresh_handle_caches()
    h = _span_hists.get(name)
    if h is None:
        h = _span_hists[name] = _registry.histogram("span/" + name)
    h.observe(dur_s)
    _goodput.note_span(name, dur_s, args)
    with _mu:
        _span_totals[name] = _span_totals.get(name, 0.0) + dur_s
        _last_span = (name, time.time(), dur_s)


def mark(name):
    """Point occurrence -> counter (``profiler.mark_event`` double-
    publishes here: compile_cache hit/miss marks become counters)."""
    if not _enabled:
        return
    _registry.counter("mark/" + name).inc()


def heartbeat(name):
    """Worker-thread liveness signal (dispatch queue, prefetch
    producer); feeds the watchdog's per-thread age map."""
    if not _enabled:
        return
    w = _watchdog
    if w is not None:
        w.heartbeat(name)


# per-program metric handles (step-time histogram + steps/seconds/
# examples counters keyed by the short fingerprint), cached like the
# span histograms; _last_fp feeds the watchdog's "suspect program" line
_prog_metrics = {}
_last_fp = [None]


def _program_handles(fp12):
    _refresh_handle_caches()
    h = _prog_metrics.get(fp12)
    if h is None:
        base = "program/" + fp12
        h = _prog_metrics[fp12] = {
            "steps": _registry.counter(base + "/steps_total"),
            "seconds": _registry.counter(base + "/step_seconds_total"),
            "examples": _registry.counter(base + "/examples_total"),
            "hist": _registry.histogram(base + "/step_seconds"),
        }
    return h


def record_step(name, step_seconds, examples, dispatch_queue_depth,
                device=None, warm=None, fingerprint=None, extras=None):
    """One executor ``run()`` completed: assemble the StepStats record,
    fold it into the aggregator/registry, append it to the JSONL log,
    and pet the watchdog.  ``warm`` is the executor's own verdict on
    this step (False = it paid a trace/compile for an unseen
    program/feed signature) — the step-level compile count a healthy
    steady-state loop drives to zero.  ``fingerprint`` is the program's
    structural fingerprint: step records, the per-program metric family
    (``program/<fp12>/...``), and the program_profile step accounting
    are all tagged with it so JSONL, /metrics, and the program report
    agree on which program did what."""
    if not _enabled:
        return None
    from .. import compile_cache

    with _mu:
        fs_total = _span_totals.get(name + "/fetch_sync", 0.0)
        fs_wait = fs_total - _last_fetch_sync.get(name, 0.0)
        _last_fetch_sync[name] = fs_total
        rec = {"event": "step_stats", "ts": time.time(), "run_id": _RUN_ID,
               "executor": name,
               "step_seconds": round(step_seconds, 6),
               "examples": int(examples) if examples else 0,
               "examples_per_sec": round(examples / step_seconds, 2)
               if examples and step_seconds > 0 else 0.0,
               "fetch_sync_wait_s": round(fs_wait, 6),
               "dispatch_queue_depth": int(dispatch_queue_depth),
               "compile_cache": compile_cache.stats(),
               "prefetch": _prefetch_state(),
               "device": _device_state(device)}
        if extras:
            # producer-supplied step-record fields (e.g. the executors'
            # sparse_touched_rows count) — JSONL-visible per step
            rec.update(extras)
        if warm is not None:
            rec["warm"] = bool(warm)
            if not warm:
                _registry.counter("monitor/steps_compiled").inc()
        if program_profile.probe_active():
            # tuner probe steps carry the tag into the JSONL so the
            # offline program_report replay and the goodput ledger
            # exclude them from steady-state attribution
            rec["probe"] = True
        if fingerprint:
            rec["fingerprint"] = fingerprint
            _last_fp[0] = fingerprint
            h = _program_handles(fingerprint[:12])
            h["steps"].inc()
            h["seconds"].inc(step_seconds)
            h["hist"].observe(step_seconds)
            if examples:
                h["examples"].inc(examples)
            program_profile.note_step(fingerprint, step_seconds, examples,
                                      kind=name)
        # attribute this step's wall clock (and the gap before it) into
        # the goodput buckets; the per-step delta rides in the record so
        # an offline replay can rebuild the attribution exactly
        gp_delta, gp_emit = _goodput.note_step(rec, now=rec["ts"])
        if gp_delta:
            rec["goodput"] = gp_delta
        rec = _aggregator.record(rec)
        w = _watchdog
        if w is not None:
            w.step_completed()
    if aggregate._ENABLED:
        # feed the fleet digest's recent-step ring (one bool read when
        # fleet telemetry is off — the disabled-is-free contract)
        aggregate.note_step_time(rec["step_seconds"], now=rec["ts"])
    log_event(rec)
    if gp_emit:
        # periodic cumulative checkpoint record: replays can trust the
        # ledger's own arithmetic, not just the per-step deltas
        log_event(dict(_goodput.summary(), event="goodput",
                       ts=time.time()))
    return rec


# per-device metric handles for ParallelExecutor's mesh gauges
_dev_metrics = {}


def sample_device_gauges(devices):
    """Publish per-device memory/step gauges for a mesh step
    (ParallelExecutor): a ``device/<platform><id>/steps_total`` counter
    per step, plus ``bytes_in_use``/``bytes_limit`` gauges served from
    ``_device_state``'s per-device sample cache — the same cadence (and
    the same cached sample) record_step's device field uses, so a
    sampled step issues one ``memory_stats()`` per device, not two."""
    if not _enabled:
        return
    _refresh_handle_caches()
    fresh = {}
    for d in devices:
        key = "%s%s" % (getattr(d, "platform", "dev"), getattr(d, "id", 0))
        h = _dev_metrics.get(key)
        if h is None:
            base = "device/" + key
            h = _dev_metrics[key] = {
                "steps": _registry.counter(base + "/steps_total"),
                "in_use": _registry.gauge(base + "/bytes_in_use"),
                "limit": _registry.gauge(base + "/bytes_limit"),
                "peak": _registry.gauge(base + "/bytes_in_use_peak"),
                "_peak": 0,
                "_calls": 0,
            }
        h["steps"].inc()
        h["_calls"] += 1
        ms = _device_state(d)
        if ms.get("bytes_in_use") is not None:
            h["in_use"].set(ms["bytes_in_use"])
            # running per-device peak: tools/program_report.py's
            # min/max-across-mesh column reads these (live or replayed)
            if ms["bytes_in_use"] > h["_peak"]:
                h["_peak"] = ms["bytes_in_use"]
                h["peak"].set(h["_peak"])
            if h["_calls"] % _DEVICE_SAMPLE_EVERY == 1:
                fresh[key] = {"bytes_in_use": ms["bytes_in_use"],
                              "bytes_limit": ms.get("bytes_limit"),
                              "bytes_in_use_peak": h["_peak"]}
        if ms.get("bytes_limit") is not None:
            h["limit"].set(ms["bytes_limit"])
    # JSONL twin of the gauges, on the same decimated cadence (the
    # _device_state sample cache refreshes every Nth step): offline
    # program_report replays these into the per-device HBM columns
    if fresh:
        log_event({"event": "device_stats", "ts": time.time(),
                   "run_id": _RUN_ID, "devices": fresh})


def _prefetch_state():
    """Aggregate occupancy over every live DevicePrefetcher."""
    occ = cap = n = 0
    for s in queue_states():
        if s.get("kind") == "prefetcher" and not s.get("stopped"):
            occ += s.get("occupancy", 0)
            cap += s.get("capacity", 0)
            n += 1
    return {"live": n, "occupancy": occ, "capacity": cap}


# device-memory sampling cadence: live_arrays() walks every live buffer
# (~10us per few hundred arrays), so StepStats re-samples every Nth step
# and carries the last sample forward — memory leaks are minutes-scale
# signals, steps can be sub-millisecond.  Keyed per device: a TPU
# training loop interleaved with CPU eval steps must not serve the CPU
# sample (usually empty) as the TPU's.
_DEVICE_SAMPLE_EVERY = 10
_device_cache = {}            # device key -> [steps since sample, sample]


def _device_state(device):
    """Device memory via jax ``memory_stats()``/``live_arrays`` when the
    backend reports them (TPU does; CPU usually returns None); sampled
    every ``_DEVICE_SAMPLE_EVERY`` steps per device."""
    key = (getattr(device, "platform", None), getattr(device, "id", None))
    cache = _device_cache.setdefault(key, [0, None])
    if cache[1] is not None and cache[0] % _DEVICE_SAMPLE_EVERY:
        cache[0] += 1
        return cache[1]
    cache[0] = 1
    out = {}
    try:
        import jax

        out["live_arrays"] = len(jax.live_arrays())
    except Exception:  # noqa: BLE001 — telemetry never breaks the step
        pass
    if device is not None:
        try:
            ms = device.memory_stats()
            if ms:
                out["bytes_in_use"] = ms.get("bytes_in_use")
                out["bytes_limit"] = ms.get("bytes_limit")
        except Exception:  # noqa: BLE001
            pass
    cache[1] = out
    return out


# ---------------------------------------------------------------------------
# watchdog sink/probe
# ---------------------------------------------------------------------------

def _fleet_stall_view():
    """Per-host digest ages, straggler verdicts, and active alerts for
    stall dumps (ISSUE 19 satellite): a "97% input_wait" dump should
    also say which peer went dark.  Only attempted when fleet telemetry
    is on AND a cluster member is registered; any transport failure
    yields None — the dump must land regardless."""
    if not aggregate._ENABLED:
        return None
    try:
        from ..cluster.runtime import local_member

        m = local_member()
        if m is None:
            return None
        view = m.fleet_view()
        hosts = view.get("hosts") or {}
        return {"digest_age_s": {h: d.get("digest_age_s")
                                 for h, d in hosts.items()},
                "stragglers": sorted(view.get("stragglers") or {}),
                "alerts": view.get("alerts") or []}
    except Exception:  # noqa: BLE001 — diagnostics must land
        return None


def _stall_probe():
    qs = queue_states()
    return {"queues": qs,
            # which peer went dark / is firing (fleet telemetry): per-
            # host digest ages + active alerts when this process is a
            # cluster member with FLAGS_fleet_telemetry on
            "fleet": _fleet_stall_view(),
            # the in-flight serving requests (trace_id, age, state) next
            # to the suspect program: a serving stall postmortem starts
            # from the stuck REQUEST, not just the stuck program
            "serving_requests": [r for s in qs
                                 if s.get("kind") == "serving_engine"
                                 for r in s.get("requests", [])],
            "last_span": _last_span,
            "last_step": _aggregator.last(),
            "compile_cache": _import_cc_stats(),
            # where the wall clock has been going: a stall report that
            # says "97% input_wait over the last window" is actionable;
            # "no step completed" is not
            "goodput": _goodput.snapshot_for_stall(),
            # the last per-layer model-health snapshot (FLAGS_health):
            # a stall that follows a gradient explosion should say so
            "health": health.last_snapshot(),
            # the suspect: fingerprint + cost/memory profile of the last
            # program a step completed for — a stall report should name
            # which compiled program the device is (probably) stuck in
            "last_program": program_profile.summary_for(_last_fp[0])}


def _import_cc_stats():
    from .. import compile_cache

    return compile_cache.stats()


# stall-escalation subscribers (the guardian registers here): each
# watchdog firing is fanned out so a policy layer can COUNT stalls and
# escalate, without the watchdog itself ever deciding anything
_stall_listeners = []


def add_stall_listener(fn):
    """Subscribe ``fn(diagnostic_dict)`` to watchdog stall firings
    (called from the watchdog thread; must not raise for long-term
    health — exceptions are swallowed like any diagnostics failure)."""
    if fn not in _stall_listeners:
        _stall_listeners.append(fn)


def remove_stall_listener(fn):
    if fn in _stall_listeners:
        _stall_listeners.remove(fn)


def _stall_sink(diag):
    _registry.counter("monitor/watchdog_stalls").inc()
    try:
        # cluster runs stamp the stall record with member id +
        # membership epoch (cross-host post-mortem correlation); the
        # guard keeps a broken cluster session from eating the dump
        from ..cluster.runtime import local_context

        for k, v in local_context().items():
            diag.setdefault(k, v)
    except Exception:  # noqa: BLE001 — diagnostics must land
        pass
    log_event(diag)
    print("[monitor] WATCHDOG: no step completed in %.1fs — pipeline "
          "stalled?\n%s" % (diag["stalled_for_s"], _format_diag(diag)),
          file=sys.stderr, flush=True)
    for fn in list(_stall_listeners):
        try:
            fn(diag)
        except Exception as e:  # noqa: BLE001 — escalation must not
            print("[monitor] stall listener failed: %r" % e,  # kill the
                  file=sys.stderr, flush=True)                # watchdog


def _format_diag(diag):
    lines = []
    for q in diag.get("queues", []):
        if q.get("kind") == "serving_engine":
            continue            # rendered per-request below
        lines.append("  queue %s" % q)
    for r in diag.get("serving_requests", []):
        lines.append("  request %-12s %-8s age %8.1fs trace %s" % (
            r.get("id"), r.get("state"), r.get("age_s") or 0.0,
            r.get("trace_id") or "-"))
    for n, age in diag.get("heartbeat_age_s", {}).items():
        lines.append("  heartbeat %-30s %8.1fs ago" % (n, age))
    if diag.get("last_span"):
        name, ts, dur = diag["last_span"]
        lines.append("  last span %s (%.3fs) at %s" % (name, dur, ts))
    gp = diag.get("goodput") or {}
    if gp.get("recent_fractions"):
        lines.append("  goodput last %d steps: %s" % (
            gp.get("recent_steps", 0),
            ", ".join("%s %d%%" % (b, round(f * 100)) for b, f
                      in gp["recent_fractions"].items())))
    if diag.get("last_program"):
        lines.append("  last program %s" % diag["last_program"])
    if diag.get("health"):
        lines.append("  health %s" % health.format_snapshot(diag["health"]))
    fleet = diag.get("fleet") or {}
    strag = set(fleet.get("stragglers") or ())
    for h, age in sorted((fleet.get("digest_age_s") or {}).items()):
        lines.append("  fleet digest %-22s %8.1fs ago%s" % (
            h, age or 0.0, "  STRAGGLER" if h in strag else ""))
    for a in fleet.get("alerts") or []:
        lines.append("  fleet alert [%s] %s%s" % (
            a.get("severity"), a.get("rule"),
            " host=%s" % a["member_id"] if a.get("member_id") else ""))
    return "\n".join(lines) if lines else "  (no pipeline state tracked)"


# imported last: program_profile's lazy `from . import ...` calls need
# nothing at its import time, and _reconcile/_stall_probe reference the
# module as an attribute at call time
from . import program_profile  # noqa: E402
# request tracing (ISSUE 17): reachable as monitor.tracing; its _emit
# imports run_id/log_event lazily, so order here is unconstrained
from . import tracing  # noqa: E402
# fleet telemetry (ISSUE 19): reachable as monitor.aggregate /
# monitor.alerts; record_step and ClusterMember gate every call on
# aggregate._ENABLED, so import order is unconstrained here too
from . import aggregate  # noqa: E402
from . import alerts  # noqa: E402
# model-health probe + NaN provenance (ISSUE 20): reachable as
# monitor.health; the executors gate every call on the compiled entry's
# probe slot, so import order is unconstrained here too
from . import health  # noqa: E402
