"""Fleet telemetry plane (ISSUE 19): cross-host metric aggregation.

Every host's monitor is process-local — the registry, the goodput
ledger, the JSONL log all describe ONE process.  The cluster control
plane (PR 13/18) already moves heartbeat metadata from every host to
one master; this module closes the gap by riding a **MetricDigest** on
that existing path (``ClusterMaster.heartbeat`` meta-merge — no new
connection, no new thread) and merging the digests master-side into
fleet-level series:

* **DigestBuilder** (host side) — a compact snapshot of the host's
  counters, gauges, fixed-bucket histograms, goodput summary, and
  recent step wall-times.  Values are CUMULATIVE and the digest is a
  *delta snapshot*: only metrics that changed since the last
  **committed** (delivered) digest are included, so a lost heartbeat
  loses nothing (the next digest re-ships the still-uncommitted
  change) and a duplicated delivery double-counts nothing (the master
  folds cumulative differences, and a replayed value differs by zero).
  A size guard decimates oldest step samples and lowest-traffic
  histograms when the serialized digest exceeds ``FLAGS_fleet_digest_bytes``
  — a fat digest must never delay lease renewal — counting each
  truncation in ``fleet/digest_truncated``.

* **FleetAggregator** (master side) — counters summed across hosts
  (contributions survive member death), gauges kept per-host plus
  min/median/max, histograms bucket-merged so fleet p50/p99 are EXACT
  (same fixed buckets everywhere: the merged counts are bit-equal to
  pooling every host's raw observations into one histogram), and a
  fleet goodput ratio (sum compute / sum wall).  Merged series publish
  into the master process's own monitor registry under ``fleet/...``
  — the existing /metrics endpoint and JSONL exporters serve them for
  free — and a periodic ``fleet_view`` JSONL record enables offline
  replay (``tools/fleet_report.py``).

* **StragglerDetector** — the guardian's rolling median/MAD idiom
  (one-sided z-score with a relative dispersion floor) applied ACROSS
  hosts to per-host step wall-time (and per-replica queue depth on
  serving fleets).  Verdicts are soft: ``FleetMaster.route()``
  consults them as a tie-break only (quarantine stays lease-driven;
  stragglers just lose ties).

The disabled path is one module-global bool read (``_ENABLED``) at
each instrumentation site — the same contract as ``monitor._enabled``
and ``fault._ACTIVE``.
"""

import collections
import json
import math
import threading
import time

from .registry import Counter, Gauge, Histogram

__all__ = [
    "DigestBuilder", "FleetAggregator", "StragglerDetector",
    "enabled", "enable", "disable", "note_step_time", "hist_percentile",
    "merge_hist_counts",
]

# fast-path gate: one module-global bool read is all a disabled process
# pays per heartbeat / per step (the disabled-is-free contract)
_ENABLED = False
_MAX_BYTES = 16384

# recent step wall-times, fed by monitor.record_step (enabled-gated
# there); the DigestBuilder drains samples newer than its committed
# high-water timestamp.  deque append is atomic under the GIL.
_STEP_RING = collections.deque(maxlen=256)


def enabled():
    """True iff fleet telemetry is on (``FLAGS_fleet_telemetry``)."""
    return _ENABLED


def _reconcile():
    """Re-read the FLAGS_fleet_telemetry family (on_set hook)."""
    global _ENABLED, _MAX_BYTES
    from .. import flags

    try:
        on = bool(flags.flag("fleet_telemetry"))
    except KeyError:
        on = False
    try:
        cap = int(flags.flag("fleet_digest_bytes"))
    except KeyError:
        cap = 16384
    if on and not _ENABLED:
        _STEP_RING.clear()
    _ENABLED = on
    _MAX_BYTES = max(1024, cap)


def enable():
    from .. import flags

    flags.set_flags({"fleet_telemetry": True})


def disable():
    from .. import flags

    flags.set_flags({"fleet_telemetry": False})


def note_step_time(step_seconds, now=None):
    """One executor step completed (called from ``monitor.record_step``
    behind the ``_ENABLED`` gate): feed the digest's recent-step ring."""
    _STEP_RING.append((time.time() if now is None else now,
                       float(step_seconds)))


# ---------------------------------------------------------------------------
# exact percentiles from fixed-bucket histograms
# ---------------------------------------------------------------------------

def hist_percentile(bounds, counts, q):
    """The q-quantile of a fixed-bucket histogram, reported as the
    upper bound of the bucket holding the q-th observation (the +Inf
    overflow reports ``inf``).  Deterministic, so bucket-merged fleet
    percentiles are bit-equal to pooling every host's observations
    into one histogram with the same bounds — the merge is just
    element-wise count addition."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = max(1, int(math.ceil(float(q) * total)))
    cum = 0
    for bound, cnt in zip(bounds, counts):
        cum += cnt
        if cum >= rank:
            return float(bound)
    return float("inf")


def merge_hist_counts(into, counts):
    """Element-wise add ``counts`` into the accumulator list."""
    for i, c in enumerate(counts):
        into[i] += c
    return into


# ---------------------------------------------------------------------------
# host side: DigestBuilder
# ---------------------------------------------------------------------------

# step samples shipped per digest, newest kept when decimating
_MAX_STEP_SAMPLES = 32
# pending (shipped, not yet committed) digests retained for commit
_MAX_PENDING = 8


class DigestBuilder:
    """Builds one host's MetricDigest per heartbeat.

    ``build()`` snapshots the registry and includes only metrics whose
    cumulative value moved since the last **committed** digest;
    ``committed(seq)`` advances the baseline once the transport
    confirmed delivery (``ClusterMember.heartbeat`` calls it after the
    RPC returns a non-rejoin view).  An undelivered digest is simply
    re-shipped — cumulative values make re-delivery idempotent."""

    def __init__(self, host_id, registry=None, max_bytes=None,
                 clock=time.time):
        self.host_id = str(host_id)
        self._registry = registry
        self._max_bytes = max_bytes
        self._clock = clock
        self._seq = 0
        self._gen = None
        # committed (known-delivered) cumulative views
        self._counters = {}       # name -> value
        self._gauges = {}         # name -> value
        self._hists = {}          # name -> count (cheap changed check)
        self._step_ts = 0.0       # high-water ts of committed step samples
        self._pending = collections.OrderedDict()  # seq -> shipped views
        self._scan = []           # cached (kind, metric) dispatch list
        self._scan_n = -1         # registry size the cache was built at
        self.truncations = 0

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from .. import monitor

        return monitor.registry()

    def _rebaseline(self, gen):
        self._counters.clear()
        self._gauges.clear()
        self._hists.clear()
        self._pending.clear()
        self._scan, self._scan_n = [], -1
        self._gen = gen

    def build(self, now=None):
        """One MetricDigest dict (JSON-safe; rides the heartbeat meta)."""
        from .. import monitor

        reg = self._reg()
        if self._gen != reg.generation:
            # registry reset (tests/operators): everything re-ships
            self._rebaseline(reg.generation)
        now = self._clock() if now is None else now
        self._seq += 1
        counters, gauges, hists = {}, {}, {}
        metrics = reg.metrics()
        if len(metrics) != self._scan_n:
            # metrics are append-only within a generation, so the kind
            # dispatch + name filter is recomputed only when one is
            # registered — per-heartbeat cost stays one len() compare
            scan = []
            for m in metrics:
                if m.name.startswith(("fleet/", "alerts/")):
                    # master-side aggregation products: a process that
                    # is both master and member must not ship its own
                    # merged series back into itself (a feedback
                    # cascade)
                    continue
                if isinstance(m, Counter):
                    scan.append((0, m))
                elif isinstance(m, Gauge):
                    scan.append((1, m))
                elif isinstance(m, Histogram):
                    scan.append((2, m))
            self._scan, self._scan_n = scan, len(metrics)
        for kind, m in self._scan:
            if kind == 0:
                v = m.value
                if v != self._counters.get(m.name, 0.0):
                    counters[m.name] = v
            elif kind == 1:
                v = m.value
                if v != self._gauges.get(m.name):
                    gauges[m.name] = v
            elif m.count != self._hists.get(m.name, 0):
                s = m.snapshot()
                hists[m.name] = {"b": s["buckets"], "c": s["counts"],
                                 "sum": round(s["sum"], 6),
                                 "n": s["count"]}
        # newest-first scan with early break: the ring is time-ordered
        # and heartbeats usually find only a handful of new samples, so
        # this is O(new), not O(ring).  copy() is C-level (atomic under
        # the GIL) — safe against the training thread's appends.
        steps = []
        for ts, sec in reversed(_STEP_RING.copy()):
            if ts <= self._step_ts or len(steps) == _MAX_STEP_SAMPLES:
                break
            steps.append((round(ts, 3), round(sec, 6)))
        steps.reverse()
        gp = monitor.goodput_summary() if self._registry is None else None
        digest = {"v": 1, "seq": self._seq, "host": self.host_id,
                  "ts": round(now, 3), "run": monitor.run_id(),
                  "counters": counters, "gauges": gauges, "hists": hists,
                  "steps": steps}
        if gp is not None:
            digest["goodput"] = {
                "compute": gp["buckets"].get("compute", 0.0),
                "wall": gp["wall_seconds"],
                "ratio": gp["goodput_ratio"],
                "steps": gp["steps"]}
        self._cap(digest)
        self._pending[self._seq] = {
            "counters": dict(digest["counters"]),
            "gauges": dict(digest["gauges"]),
            "hists": {n: h["n"] for n, h in digest["hists"].items()},
            "step_ts": (digest["steps"][-1][0]
                        if digest["steps"] else self._step_ts)}
        while len(self._pending) > _MAX_PENDING:
            self._pending.popitem(last=False)
        return digest

    def committed(self, seq):
        """The transport delivered digest ``seq``: advance the baseline
        (this and every older pending digest is subsumed — values are
        cumulative, so the newest delivered view wins)."""
        found = False
        for s in list(self._pending):
            if s > seq:
                break
            shipped = self._pending.pop(s)
            self._counters.update(shipped["counters"])
            self._gauges.update(shipped["gauges"])
            self._hists.update(shipped["hists"])
            self._step_ts = max(self._step_ts, shipped["step_ts"])
            found = s == seq or found
        return found

    # -- satellite: heartbeat payload size guard -----------------------
    def _cap(self, digest):
        """Decimate the digest until it fits the byte budget: halve the
        step samples (oldest dropped first), then drop the
        lowest-traffic histograms — dropped metrics stay uncommitted
        and re-ship next digest, so decimation defers, never loses."""
        cap = self._max_bytes if self._max_bytes is not None else _MAX_BYTES
        # cheap upper-bound estimate before paying a json.dumps: names +
        # per-entry framing + per-bucket digits
        est = 96
        for n in digest["counters"]:
            est += len(n) + 20
        for n in digest["gauges"]:
            est += len(n) + 20
        for n, h in digest["hists"].items():
            est += len(n) + 40 + 8 * (len(h["b"]) + len(h["c"]))
        est += 22 * len(digest["steps"])
        if est <= cap:
            return
        from .. import monitor

        truncated = False
        while True:
            size = len(json.dumps(digest, separators=(",", ":")))
            if size <= cap:
                break
            if len(digest["steps"]) > 2:
                digest["steps"] = digest["steps"][
                    len(digest["steps"]) // 2:]
            elif digest["hists"]:
                drop = min(digest["hists"],
                           key=lambda n: digest["hists"][n]["n"])
                del digest["hists"][drop]
            elif len(digest["counters"]) > 8 or len(digest["gauges"]) > 8:
                for fam in ("gauges", "counters"):
                    names = sorted(digest[fam])[8:]
                    for n in names:
                        del digest[fam][n]
            else:
                break              # minimal digest; ship it regardless
            truncated = True
        if truncated:
            digest["trunc"] = True
            self.truncations += 1
            monitor.count("fleet/digest_truncated")


# ---------------------------------------------------------------------------
# straggler detection: guardian's median/MAD idiom, across hosts
# ---------------------------------------------------------------------------

def _median(vals):
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


class StragglerDetector:
    """One-sided median/MAD outlier detection across hosts.

    For each series (step wall-time, serving queue depth) the fleet
    median and MAD define ``z = (v - med) / (1.4826*MAD + floor)``
    with a dispersion floor RELATIVE to the level (guardian.py: a
    saturated window — every healthy host bit-identical — must not
    turn float noise into z ~ 1e4).  A host is a straggler after
    ``persist`` consecutive windows over ``zmax``; the flag clears on
    the first in-band window.  Fewer than ``min_hosts`` reporting
    hosts yields no verdicts — a 2-host MAD is degenerate."""

    def __init__(self, zmax=8.0, persist=2, min_hosts=3, rel_floor=0.05):
        self.zmax = float(zmax)
        self.persist = int(persist)
        self.min_hosts = int(min_hosts)
        self.rel_floor = float(rel_floor)
        self._runs = {}       # (series, host) -> consecutive over-z count
        self._flagged = {}    # host -> {"series", "z", "since"}

    def update(self, series_map, now):
        """``series_map``: {series_name: {host: latest window value}}.
        Recomputes verdicts; returns the set of flagged hosts."""
        seen = set()
        for series, vals in series_map.items():
            if len(vals) < self.min_hosts:
                for key in [k for k in self._runs if k[0] == series]:
                    del self._runs[key]
                continue
            med = _median(list(vals.values()))
            mad = _median([abs(v - med) for v in vals.values()])
            denom = 1.4826 * mad + self.rel_floor * max(abs(med), 1e-9)
            for host, v in vals.items():
                z = (v - med) / denom
                key = (series, host)
                if z > self.zmax:
                    self._runs[key] = self._runs.get(key, 0) + 1
                    if self._runs[key] >= self.persist:
                        cur = self._flagged.get(host)
                        if cur is None or cur["z"] < z:
                            self._flagged[host] = {
                                "series": series, "z": round(z, 2),
                                "since": (cur or {}).get("since", now)}
                        seen.add(host)
                else:
                    self._runs.pop(key, None)
                    cur = self._flagged.get(host)
                    if cur is not None and cur["series"] == series:
                        del self._flagged[host]
        # hosts flagged by a series that no longer reports them unflag
        for host in [h for h in self._flagged if h not in seen
                     and not any(self._runs.get((s, h), 0) >= self.persist
                                 for s in series_map)]:
            self._flagged.pop(host, None)
        return set(self._flagged)

    def verdicts(self):
        return {h: dict(v) for h, v in self._flagged.items()}

    def hosts(self):
        return frozenset(self._flagged)

    def remove(self, host):
        self._flagged.pop(host, None)
        for key in [k for k in self._runs if k[1] == host]:
            del self._runs[key]


# ---------------------------------------------------------------------------
# master side: FleetAggregator
# ---------------------------------------------------------------------------

class _HostState:
    __slots__ = ("last_seq", "last_ts", "digest_ts", "run",
                 "counters", "hists", "gauges", "goodput",
                 "step_samples", "window_vals", "queue_depth",
                 "ckpt_last_move", "ckpt_seen", "joined_ts", "live")

    def __init__(self, now):
        self.live = True
        self.last_seq = 0
        self.last_ts = now           # master-clock arrival time
        self.digest_ts = None        # host-clock digest build time
        self.run = None
        self.counters = {}           # name -> last cumulative value
        self.hists = {}              # name -> last cumulative counts
        self.gauges = {}
        self.goodput = {"compute": 0.0, "wall": 0.0, "ratio": None,
                        "steps": 0}
        self.step_samples = collections.deque(maxlen=64)
        self.window_vals = collections.deque(maxlen=16)
        self.queue_depth = None
        self.ckpt_last_move = None
        self.ckpt_seen = False
        self.joined_ts = now


# tombstone retention for expired/quarantined hosts (alert lifecycle:
# the alert resolves when the host rejoins or the tombstone ages out)
_TOMBSTONE_S = 600.0
# per-host gauges published into the master registry (the full gauge
# set stays reachable via fleet_view; publishing every per-host gauge
# would flood /metrics)
_HOST_GAUGES = ("step_time_s", "goodput_ratio", "queue_depth",
                "straggler")


class FleetAggregator:
    """Merges member digests into fleet-level series (master side).

    Attach to any ClusterMaster/FleetMaster via the constructor (or
    ``master.attach_telemetry(agg)``): the master feeds digests popped
    from heartbeat meta into ``ingest`` and notifies membership exits.
    Thread-safe; never raises into the control plane."""

    def __init__(self, master=None, clock=None, rules=None,
                 detector=None, stale_after=None, emit_every=10):
        from . import alerts

        self._clock = clock or (master._clock if master is not None
                                else time.time)
        self._mu = threading.RLock()
        self._hosts = {}             # host -> _HostState (live)
        self._expired = {}           # host -> expiry ts (tombstones)
        self._quarantined = {}       # host -> quarantine ts
        self._counters = {}          # fleet totals (survive host death)
        self._hists = {}             # name -> {"b": tuple, "c": [..],
                                     #          "sum": f, "n": int}
        self._goodput = {"compute": 0.0, "wall": 0.0}
        self.detector = detector or StragglerDetector()
        self.engine = alerts.AlertEngine(
            alerts.default_rules() if rules is None else rules,
            clock=self._clock)
        # digests older than this (no fresh window) drop out of the
        # straggler comparison and read as dark in the view
        self._stale_after = float(stale_after if stale_after is not None
                                  else (3.0 * master.lease_timeout
                                        if master is not None else 30.0))
        self._emit_every = int(emit_every)
        self._ingests = 0
        self._pub = {}               # published-handle cache
        self._pub_gen = None
        if master is not None:
            master.attach_telemetry(self)

    # -- ingestion ------------------------------------------------------
    def ingest(self, host_id, digest, meta=None, now=None):
        """Apply one member digest.  Late/out-of-order/duplicate digests
        (seq <= last applied for the host's run token) are dropped —
        cumulative values make the ordering guard sufficient for
        exactly-once folding."""
        from .. import monitor

        host_id = str(host_id)
        if not isinstance(digest, dict) or "seq" not in digest:
            return False
        events = []
        with self._mu:
            now = self._clock() if now is None else now
            hs = self._hosts.get(host_id)
            if hs is None:
                hs = self._hosts[host_id] = _HostState(now)
            run = digest.get("run")
            if run != hs.run:
                # new process incarnation: cumulative views restart
                hs.counters.clear()
                hs.hists.clear()
                hs.gauges.clear()
                hs.goodput = {"compute": 0.0, "wall": 0.0,
                              "ratio": None, "steps": 0}
                hs.run = run
                hs.last_seq = 0
            seq = int(digest["seq"])
            if seq <= hs.last_seq:
                monitor.count("fleet/digest_stale")
                return False
            hs.live = True
            hs.last_seq = seq
            hs.last_ts = now
            hs.digest_ts = digest.get("ts")
            # a rejoin clears the tombstones: the alert resolves
            self._expired.pop(host_id, None)
            self._quarantined.pop(host_id, None)
            ckpt_moved = False
            for name, v in (digest.get("counters") or {}).items():
                prev = hs.counters.get(name, 0.0)
                diff = v - prev if v >= prev else v
                hs.counters[name] = v
                self._counters[name] = self._counters.get(name, 0.0) + diff
                if diff > 0 and "checkpoint" in name:
                    ckpt_moved = True
            for name, h in (digest.get("hists") or {}).items():
                bounds = tuple(h["b"])
                fleet = self._hists.get(name)
                if fleet is None:
                    fleet = self._hists[name] = {
                        "b": bounds, "c": [0] * len(h["c"]),
                        "sum": 0.0, "n": 0}
                if fleet["b"] != bounds or len(fleet["c"]) != len(h["c"]):
                    # a version-skewed member's layout cannot merge
                    # exactly; drop rather than corrupt the percentile
                    monitor.count("fleet/digest_bucket_mismatch")
                    continue
                prev = hs.hists.get(name)
                if prev is None or prev["n"] > h["n"] \
                        or len(prev["c"]) != len(h["c"]):
                    prev = {"c": [0] * len(h["c"]), "sum": 0.0, "n": 0}
                merge_hist_counts(
                    fleet["c"], [c - p for c, p in zip(h["c"], prev["c"])])
                fleet["sum"] += h["sum"] - prev["sum"]
                fleet["n"] += h["n"] - prev["n"]
                hs.hists[name] = {"c": list(h["c"]), "sum": h["sum"],
                                  "n": h["n"]}
                if h["n"] > prev["n"] and "checkpoint" in name:
                    ckpt_moved = True
            if ckpt_moved:
                hs.ckpt_last_move = now
                hs.ckpt_seen = True
            hs.gauges.update(digest.get("gauges") or {})
            gp = digest.get("goodput")
            if gp:
                for k in ("compute", "wall"):
                    prev = hs.goodput.get(k, 0.0)
                    v = float(gp.get(k) or 0.0)
                    self._goodput[k] += v - prev if v >= prev else v
                    hs.goodput[k] = v
                hs.goodput["ratio"] = gp.get("ratio")
                hs.goodput["steps"] = gp.get("steps", 0)
            steps = digest.get("steps") or ()
            for ts, sec in steps:
                hs.step_samples.append((ts, sec))
            if steps:
                hs.window_vals.append(
                    sum(s for _, s in steps) / float(len(steps)))
            load = (meta or {}).get("load") or {}
            if load.get("queue_depth") is not None:
                hs.queue_depth = int(load["queue_depth"])
            self._ingests += 1
            self.detector.update(self._detector_series(now), now)
            self._publish()
            view = self._view_locked(now)
            events = self.engine.evaluate(view, now)
            emit = (self._ingests % self._emit_every == 0) or events
        for e in events:
            monitor.log_event(e)
        if emit:
            monitor.log_event(dict(view, event="fleet_view"))
        return True

    def _detector_series(self, now):
        fresh = {h: s for h, s in self._hosts.items()
                 if s.live and now - s.last_ts <= self._stale_after}
        return {
            "step_time": {h: s.window_vals[-1] for h, s in fresh.items()
                          if s.window_vals},
            "queue_depth": {h: float(s.queue_depth)
                            for h, s in fresh.items()
                            if s.queue_depth is not None},
        }

    # -- membership notifications (master calls these) ------------------
    def note_expired(self, hosts, now=None):
        """Lease-expired members: gauges/step state drop, counter
        contributions stay folded, and a tombstone drives the
        lease-expiry alert until rejoin or retention.  Evaluates the
        alert rules immediately — a death with no subsequent digest
        traffic must still fire."""
        with self._mu:
            now = self._clock() if now is None else now
            for h in hosts:
                self._expired[str(h)] = now
                self._drop_locked(str(h))
            events = self.engine.evaluate(self._view_locked(now), now)
        self._log_events(events)

    def note_quarantined(self, host, now=None):
        """A FleetMaster quarantined a replica (lease-driven): feeds the
        replica-quarantine alert rule (evaluated immediately)."""
        with self._mu:
            now = self._clock() if now is None else now
            self._quarantined[str(host)] = now
            events = self.engine.evaluate(self._view_locked(now), now)
        self._log_events(events)

    @staticmethod
    def _log_events(events):
        from .. import monitor

        for e in events:
            monitor.log_event(e)

    def drop_host(self, host):
        """Graceful departure (leave): per-host state drops silently —
        no tombstone, no alert."""
        with self._mu:
            self._drop_locked(str(host))

    def _drop_locked(self, host):
        hs = self._hosts.get(host)
        if hs is not None:
            # dead, not deleted: the counter/hist baselines stay — a
            # rejoining SAME process (same run token) must diff against
            # what was already folded, not re-fold its cumulative
            # totals; a restarted process rebaselines via its fresh run
            # token.  Point-in-time state (gauges, step windows, queue
            # depth) drops out of every view immediately.
            hs.live = False
            hs.gauges.clear()
            hs.window_vals.clear()
            hs.step_samples.clear()
            hs.queue_depth = None
        self.detector.remove(host)

    # -- views ----------------------------------------------------------
    def straggler_hosts(self):
        """Current straggler verdicts as a frozenset of host ids — the
        soft deprioritization FleetMaster.route() consults."""
        with self._mu:
            return self.detector.hosts()

    def percentile(self, hist_name, q):
        """Exact fleet percentile of a merged histogram (or None)."""
        with self._mu:
            h = self._hists.get(hist_name)
            if h is None:
                return None
            return hist_percentile(h["b"], h["c"], q)

    def fleet_view(self, now=None):
        """The operator's one-pane view: per-host table, merged series,
        straggler verdicts, tombstones, active alerts.  JSON-safe (it
        is the ``fleet_view`` RPC response and JSONL record)."""
        with self._mu:
            return self._view_locked(self._clock() if now is None
                                     else now)

    def _view_locked(self, now):
        self._gc_tombstones(now)
        verdicts = self.detector.verdicts()
        hosts = {}
        for h, s in self._hosts.items():
            if not s.live:
                continue
            v = verdicts.get(h)
            hosts[h] = {
                "digest_age_s": round(now - s.last_ts, 3),
                "seq": s.last_seq,
                "step_time_s": (round(s.window_vals[-1], 6)
                                if s.window_vals else None),
                "steps_recent": len(s.step_samples),
                "goodput_ratio": s.goodput.get("ratio"),
                "queue_depth": s.queue_depth,
                "straggler": v is not None,
                "z": v["z"] if v else None,
                "checkpoint_age_s": (round(now - s.ckpt_last_move, 3)
                                     if s.ckpt_seen else None),
            }
            # model-health summary from the host's health/<layer>/<stat>
            # gauges (the FLAGS_health probe rides the digest's registry
            # snapshot): worst-layer view the grad-norm/update-ratio
            # alert rules select on
            health = {}
            for name, val in s.gauges.items():
                if name.startswith("health/"):
                    parts = name.split("/")
                    if len(parts) == 3:
                        health.setdefault(parts[1], {})[parts[2]] = val
            if health:
                worst = max(health,
                            key=lambda lb: health[lb].get("grad_norm", 0.0))
                ratios = [d["update_ratio"] for d in health.values()
                          if d.get("update_ratio") is not None]
                hosts[h]["health"] = {
                    "grad_norm_max": health[worst].get("grad_norm", 0.0),
                    "worst_layer": worst,
                    "update_ratio_min": min(ratios) if ratios else None,
                    "nonfinite_total": sum(d.get("nonfinite", 0) or 0
                                           for d in health.values()),
                    "layers": health,
                }
        wall = self._goodput["wall"]
        pcts = {}
        for name, h in self._hists.items():
            if h["n"]:
                pcts[name] = {"p50": hist_percentile(h["b"], h["c"], 0.50),
                              "p99": hist_percentile(h["b"], h["c"], 0.99),
                              "count": h["n"]}
        return {
            "ts": round(now, 3),
            "hosts": hosts,
            "goodput_ratio": (round(self._goodput["compute"] / wall, 4)
                              if wall > 0 else None),
            "counters": {n: v for n, v in self._counters.items()},
            "percentiles": pcts,
            "stragglers": verdicts,
            "expired": {h: round(now - t, 3)
                        for h, t in self._expired.items()},
            "quarantined": {h: round(now - t, 3)
                            for h, t in self._quarantined.items()},
            "alerts": self.engine.active(),
        }

    def _gc_tombstones(self, now):
        for d in (self._expired, self._quarantined):
            for h in [h for h, t in d.items()
                      if now - t > _TOMBSTONE_S]:
                del d[h]
        # dead host states (kept for rejoin baselines) age out too
        for h in [h for h, s in self._hosts.items()
                  if not s.live and now - s.last_ts > _TOMBSTONE_S]:
            del self._hosts[h]

    # -- master-registry publication ------------------------------------
    def _publish(self):
        """Mirror merged series into the master process's own monitor
        registry (enabled-gated): the existing /metrics endpoint and
        JSONL snapshots then serve the fleet series for free."""
        from .. import monitor

        if not monitor.enabled():
            return
        reg = monitor.registry()
        if self._pub_gen != reg.generation:
            self._pub.clear()
            self._pub_gen = reg.generation
        for name, total in self._counters.items():
            key = "c/" + name
            h = self._pub.get(key)
            if h is None:
                h = self._pub[key] = [reg.counter("fleet/" + name), 0.0]
            if total > h[1]:
                h[0].inc(total - h[1])
                h[1] = total
        live = {h: s for h, s in self._hosts.items() if s.live}
        gauge_names = set()
        for s in live.values():
            gauge_names.update(s.gauges)
        for name in gauge_names:
            vals = [s.gauges[name] for s in live.values()
                    if name in s.gauges]
            if not vals:
                continue
            for suffix, v in (("min", min(vals)),
                              ("med", _median(vals)),
                              ("max", max(vals))):
                key = "g/%s/%s" % (name, suffix)
                h = self._pub.get(key)
                if h is None:
                    h = self._pub[key] = reg.gauge(
                        "fleet/%s/%s" % (name, suffix))
                h.set(v)
        for name, fh in self._hists.items():
            if not fh["n"]:
                continue
            for q, label in ((0.50, "p50"), (0.99, "p99")):
                key = "p/%s/%s" % (name, label)
                h = self._pub.get(key)
                if h is None:
                    h = self._pub[key] = reg.gauge(
                        "fleet/%s/%s" % (name, label))
                p = hist_percentile(fh["b"], fh["c"], q)
                if p is not None and not math.isinf(p):
                    h.set(p)
        strag = self.detector.hosts()
        for host, s in live.items():
            derived = {
                "step_time_s": s.window_vals[-1] if s.window_vals
                else None,
                "goodput_ratio": s.goodput.get("ratio"),
                "queue_depth": s.queue_depth,
                "straggler": 1.0 if host in strag else 0.0,
            }
            for name in _HOST_GAUGES:
                v = derived.get(name)
                if v is None:
                    continue
                key = "h/%s/%s" % (host, name)
                h = self._pub.get(key)
                if h is None:
                    h = self._pub[key] = reg.gauge(
                        "fleet/host/%s/%s" % (host, name))
                h.set(v)
        wall = self._goodput["wall"]
        for key, v in (("fleet/goodput_ratio",
                        self._goodput["compute"] / wall if wall > 0
                        else None),
                       ("fleet/hosts", float(len(live))),
                       ("fleet/stragglers", float(len(strag))),
                       ("fleet/alerts_active",
                        float(len(self.engine.active())))):
            if v is None:
                continue
            h = self._pub.get(key)
            if h is None:
                h = self._pub[key] = reg.gauge(key)
            h.set(v)
