"""StepStats: the per-``run()`` telemetry record and its aggregator.

Each executor step produces one record — step wall time, examples/sec,
fetch-sync wait, retrace/compile counters and cache hit ratio,
dispatch-queue depth, prefetcher occupancy, and device memory when the
backend reports it.  The aggregator publishes every record into the
metrics registry (histogram + counters + gauges) and keeps running
aggregates so the console reporter and bench.py can emit a one-dict
summary without replaying the JSONL log.
"""

import time

__all__ = ["StepStatsAggregator"]


class StepStatsAggregator:
    """Folds per-step records into registry metrics + running totals.

    Not itself thread-safe by design: steps are recorded from the
    training thread(s) through ``monitor.record_step``, which serializes
    under the monitor lock.
    """

    def __init__(self, registry):
        self._registry = registry
        self.reset()

    def reset(self):
        self._steps = 0
        self._examples = 0.0
        self._compiled_steps = 0
        self._step_seconds_total = 0.0
        self._fetch_sync_total = 0.0
        self._last = None
        self._t_first = None
        self._t_last = None
        # metric handles bind lazily on the first record and are cached
        # until the next reset(): the registry's get-or-create lock is
        # off the per-step path, a disabled process never materializes
        # the metrics, and reset() after a registry.reset() re-binds
        self._m_steps = None
        self._bound_gen = -1

    def _bind(self):
        r = self._registry
        self._bound_gen = r.generation
        self._m_steps = r.counter("monitor/steps_total")
        self._m_examples = r.counter("monitor/examples_total")
        self._m_step_s = r.histogram("monitor/step_seconds")
        self._m_qdepth = r.gauge("monitor/dispatch_queue_depth")
        self._m_occ = r.gauge("monitor/prefetch_occupancy")
        self._m_hit = r.gauge("monitor/compile_cache_hit_ratio")
        self._m_bytes = r.gauge("monitor/device_bytes_in_use")
        self._m_live = r.gauge("monitor/device_live_arrays")

    # ------------------------------------------------------------------
    def record(self, rec):
        """Fold one StepStats record (a plain dict) into the aggregates
        and the registry; returns the record for the exporters."""
        now = rec.get("ts", time.time())
        if self._t_first is None:
            self._t_first = now
        self._t_last = now
        self._steps += 1
        rec["step"] = self._steps
        if rec.get("warm") is False:
            self._compiled_steps += 1
        self._examples += rec.get("examples", 0) or 0
        dt = rec.get("step_seconds", 0.0) or 0.0
        self._step_seconds_total += dt
        self._fetch_sync_total += rec.get("fetch_sync_wait_s", 0.0) or 0.0
        self._last = rec

        if self._m_steps is None \
                or self._bound_gen != self._registry.generation:
            self._bind()
        self._m_steps.inc()
        if rec.get("examples"):
            self._m_examples.inc(rec["examples"])
        self._m_step_s.observe(dt)
        self._m_qdepth.set(rec.get("dispatch_queue_depth", 0) or 0)
        pf = rec.get("prefetch") or {}
        self._m_occ.set(pf.get("occupancy", 0))
        cc = rec.get("compile_cache") or {}
        if "hit_ratio" in cc:
            self._m_hit.set(cc["hit_ratio"])
        dev = rec.get("device") or {}
        if dev.get("bytes_in_use") is not None:
            self._m_bytes.set(dev["bytes_in_use"])
        if dev.get("live_arrays") is not None:
            self._m_live.set(dev["live_arrays"])
        return rec

    # ------------------------------------------------------------------
    @property
    def steps(self):
        return self._steps

    def last(self):
        """The most recent StepStats record (None before the first)."""
        return self._last

    def summary(self):
        """Aggregate view for the console reporter and bench artifacts.
        Reads fields into locals first: the console thread summarizes
        concurrently with a training-thread reset()."""
        steps, examples = self._steps, self._examples
        total, t0, t1 = self._step_seconds_total, self._t_first, self._t_last
        last = self._last
        out = {"steps": steps,
               "examples": examples,
               "steps_compiled": self._compiled_steps,
               "step_seconds_total": round(total, 6),
               "fetch_sync_wait_s_total": round(self._fetch_sync_total, 6)}
        if steps:
            out["mean_step_seconds"] = round(total / steps, 6)
        wall = (t1 - t0) if t0 is not None and t1 is not None else 0.0
        if wall > 0 and examples:
            # throughput over the whole recorded span: under async
            # dispatch per-record examples/sec measures host dispatch
            # rate; the span-wide rate is the honest steady-state number
            out["examples_per_sec"] = round(examples / wall, 2)
        if last is not None:
            for k in ("compile_cache", "dispatch_queue_depth", "prefetch",
                      "device"):
                if last.get(k) is not None:
                    out["last_" + k] = last[k]
        return out
