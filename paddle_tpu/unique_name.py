"""Unique name generator for program variables and ops.

Capability parity with the reference's ``python/paddle/fluid/unique_name.py``
(name uniquifying with prefix counters, guard-based scoping) — re-designed, not
ported: a plain counter map per generator with context-manager switching.
"""

import contextlib

__all__ = ["generate", "switch", "guard"]


class UniqueNameGenerator:
    """Generates names like ``fc_0.w_0``, ``tmp_3`` from per-prefix counters."""

    def __init__(self, prefix=""):
        self.prefix = prefix
        self.ids = {}

    def __call__(self, key):
        if key not in self.ids:
            self.ids[key] = 0
        tmp = self.ids[key]
        self.ids[key] += 1
        return self.prefix + "_".join([key, str(tmp)])


generator = UniqueNameGenerator()


def generate(key):
    return generator(key)


def switch(new_generator=None):
    global generator
    old = generator
    generator = new_generator if new_generator is not None else UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
