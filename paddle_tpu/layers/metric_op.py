"""Metric layers: accuracy, auc (reference ``layers/metric_op.py``)."""

from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer

__all__ = ["accuracy", "auc"]


def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy (reference metric_op.py:accuracy = top_k + accuracy
    op)."""
    helper = LayerHelper("accuracy")
    topk_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    topk_indices = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="top_k", inputs={"X": [input]},
        outputs={"Out": [topk_out], "Indices": [topk_indices]},
        attrs={"k": k},
    )
    acc_out = helper.create_variable_for_type_inference(dtype="float32")
    if correct is None:
        correct = helper.create_variable_for_type_inference(dtype="int32")
    if total is None:
        total = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(
        type="accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices],
                "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct],
                 "Total": [total]},
    )
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1):
    """Streaming AUC with persistable histogram state
    (reference metric_op.py:auc / auc_op.cc)."""
    helper = LayerHelper("auc")
    bins = num_thresholds + 1
    stat_pos = helper.create_global_variable(
        name=helper.name + ".stat_pos", persistable=True, shape=[bins],
        dtype="int64",
    )
    stat_neg = helper.create_global_variable(
        name=helper.name + ".stat_neg", persistable=True, shape=[bins],
        dtype="int64",
    )
    for var in [stat_pos, stat_neg]:
        helper.set_variable_initializer(var, ConstantInitializer(0))
    auc_out = helper.create_variable_for_type_inference(dtype="float64")
    pos_out = helper.create_variable_for_type_inference(dtype="int64")
    neg_out = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="auc",
        inputs={"Predict": [input], "Label": [label],
                "StatPos": [stat_pos], "StatNeg": [stat_neg]},
        outputs={"AUC": [auc_out], "StatPosOut": [stat_pos],
                 "StatNegOut": [stat_neg]},
        attrs={"curve": curve, "num_thresholds": num_thresholds},
    )
    return auc_out, (stat_pos, stat_neg)
