"""Operator overloading on Variable (reference ``layers/math_op_patch.py``):
``a + b``, ``a - 1.0``, ``x.astype``, comparisons — each overload appends an
elementwise/scale op to the current program."""

from ..framework import Variable
from ..layer_helper import LayerHelper
from ..core import convert_dtype

__all__ = ["monkey_patch_variable"]


def _create_scalar_broadcast(block, value, ref_var):
    helper = LayerHelper("scalar")
    out = helper.create_variable_for_type_inference(dtype=ref_var.dtype)
    helper.append_op(
        type="fill_constant_batch_size_like"
        if ref_var.shape and ref_var.shape[0] == -1 else "fill_constant",
        inputs={"Input": [ref_var]} if ref_var.shape and ref_var.shape[0] == -1
        else {},
        outputs={"Out": [out]},
        attrs={
            "shape": [1] if not (ref_var.shape and ref_var.shape[0] == -1)
            else list(ref_var.shape),
            "value": float(value),
            "dtype": str(ref_var.dtype),
        },
    )
    return out


def _binary(op_type, reverse=False):
    def impl(self, other):
        helper = LayerHelper(op_type)
        if isinstance(other, (int, float)):
            if op_type == "elementwise_add":
                from .ops import scale

                return scale(self, scale=1.0, bias=float(other))
            if op_type == "elementwise_sub" and not reverse:
                from .ops import scale

                return scale(self, scale=1.0, bias=-float(other))
            if op_type == "elementwise_mul":
                from .ops import scale

                return scale(self, scale=float(other))
            other = _create_scalar_broadcast(self.block, other, self)
        x, y = (other, self) if reverse else (self, other)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        axis = -1
        if len(y.shape or ()) < len(x.shape or ()):
            axis = -1
        helper.append_op(
            type=op_type, inputs={"X": [x], "Y": [y]},
            outputs={"Out": [out]}, attrs={"axis": axis},
        )
        return out

    return impl


def _astype(self, dtype):
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="cast", inputs={"X": [self]}, outputs={"Out": [out]},
        attrs={"out_dtype": str(convert_dtype(dtype))},
    )
    return out


def _neg(self):
    from .ops import scale

    return scale(self, scale=-1.0)


def monkey_patch_variable():
    Variable.__add__ = _binary("elementwise_add")
    Variable.__radd__ = _binary("elementwise_add")
    Variable.__sub__ = _binary("elementwise_sub")
    Variable.__rsub__ = _binary("elementwise_sub", reverse=True)
    Variable.__mul__ = _binary("elementwise_mul")
    Variable.__rmul__ = _binary("elementwise_mul")
    Variable.__truediv__ = _binary("elementwise_div")
    Variable.__rtruediv__ = _binary("elementwise_div", reverse=True)
    Variable.__pow__ = _binary("elementwise_pow")
    Variable.__lt__ = _binary("less_than")
    Variable.__le__ = _binary("less_equal")
    Variable.__gt__ = _binary("greater_than")
    Variable.__ge__ = _binary("greater_equal")
    Variable.__neg__ = _neg
    Variable.astype = _astype
