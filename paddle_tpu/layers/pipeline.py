"""Pipeline-parallel region DSL — program-surface pipeline parallelism.

The reference has NO pipeline parallelism (SURVEY.md §2.4: absent); this
is the capability extension that makes the ``pp`` mesh axis reachable
from the Program surface, following the same sub-block pattern as
StaticRNN/While (reference ``control_flow.py:429,654``): the model
builder appends each stage's layers inside ``with pipe.stage():`` blocks,
and closing the region emits ONE ``pipeline_region`` op whose kernel
(``ops/pipeline_region.py``) runs the stages sequentially on a single
device and as a GPipe microbatch schedule over the mesh's ``pp`` axis
under the ParallelExecutor — bit-identical losses either way.

::

    pipe = Pipeline(microbatches=4)
    x = embedding_out                     # [B, T, D] carry
    for i in range(n_layer):
        with pipe.stage():
            h = pipe.carry(x)             # stage's carry-in placeholder
            ln = pipe.side(src_len)       # per-microbatch side input [B,...]
            h2 = ...layers using h, ln... # this stage's ops + params
            pipe.emit(h2)                 # stage's carry-out
    out = pipe()                          # [B, T, D]

Constraints (validated at build/lowering time): every stage must append
the SAME op-type sequence (the stages are structurally identical, only
their parameters differ — true of repeated transformer blocks); the
carry keeps one shape; nothing inside a stage may mix rows across the
batch dim (each microbatch must be independent).
"""

import contextlib

from ..framework import Variable
from ..layer_helper import LayerHelper
from .. import unique_name

__all__ = ["Pipeline"]


class Pipeline:
    def __init__(self, microbatches=None, name=None):
        self.helper = LayerHelper("pipeline", name=name)
        self.microbatches = microbatches
        self.sub_block = None
        self.parent_block = None
        self._stage_bounds = []      # op count at each stage close
        self._carry_init = None      # outer Variable feeding stage 0
        self._carry_in = []          # per-stage in-block placeholder names
        self._carry_out = []         # per-stage carry-out names
        self._sides = []             # outer side Variables (ordered)
        self._in_stage = False
        self._done = False

    # ------------------------------------------------------------------
    @contextlib.contextmanager
    def stage(self):
        if self._done:
            raise RuntimeError("pipeline already closed")
        if self._in_stage:
            raise RuntimeError("stages cannot nest")
        program = self.helper.main_program
        if self.sub_block is None:
            self.parent_block = program.current_block()
            self.sub_block = program._create_block()
        else:
            program.current_block_idx = self.sub_block.idx
        self._in_stage = True
        n_before = len(self._carry_in)
        try:
            yield
        finally:
            program.current_block_idx = self.parent_block.idx
            self._in_stage = False
        if len(self._carry_in) != n_before + 1 or \
                len(self._carry_out) != n_before + 1:
            raise ValueError(
                "each stage must call carry() once and emit() once")
        self._stage_bounds.append(len(self.sub_block.ops))

    def carry(self, init=None):
        """Stage's carry-in placeholder.  Stage 0 must pass the outer init
        Variable; later stages chain from the previous stage and must pass
        None (or the same init, for loop-friendly builders)."""
        if not self._in_stage:
            raise RuntimeError("carry() only inside stage()")
        if not self._carry_in:
            if init is None:
                raise ValueError("stage 0 needs carry(init=<outer var>)")
            self._carry_init = init
        elif init is not None and init.name != self._carry_init.name:
            raise ValueError(
                "carry(init=%r) on stage %d: the carry chains from the "
                "previous stage's emit(); only stage 0 takes an init "
                "(got a different var than stage 0's %r)"
                % (init.name, len(self._carry_in), self._carry_init.name))
        ref = self._carry_init
        v = self.sub_block.create_var(
            name=unique_name.generate(ref.name + "@pipe_in"),
            shape=tuple(ref.shape), dtype=ref.dtype)
        self._carry_in.append(v.name)
        return v

    def side(self, var):
        """Register an outer per-batch side input ([B, ...]); each stage
        sees its current microbatch's slice.  Returns the var (ops inside
        the stage reference it by its outer name)."""
        if not isinstance(var, Variable):
            raise TypeError("side() needs a Variable")
        if var.name not in [v.name for v in self._sides]:
            self._sides.append(var)
        return var

    def emit(self, var):
        if not self._in_stage:
            raise RuntimeError("emit() only inside stage()")
        if len(self._carry_out) >= len(self._carry_in):
            raise RuntimeError("emit() already called in this stage")
        if tuple(var.shape) != tuple(self._carry_init.shape):
            raise ValueError(
                "carry shape must stay constant across stages: init %s, "
                "stage %d emits %s" % (tuple(self._carry_init.shape),
                                       len(self._carry_out),
                                       tuple(var.shape)))
        self._carry_out.append(var.name)

    # ------------------------------------------------------------------
    def __call__(self):
        if self._done:
            raise RuntimeError("pipeline already closed")
        if not self._carry_out:
            raise ValueError("pipeline has no stages")
        self._done = True
        from ..core import dtype_is_floating
        from .control_flow import _classify_externals

        stages = len(self._carry_out)
        bound = set(self._carry_in) | {v.name for v in self._sides}
        floats, others = _classify_externals(self.sub_block, bound)
        # persistable floats (parameters) stack per stage; everything else
        # rides the Consts slot replicated
        params, consts = [], list(others)
        for n in floats:
            v = self.sub_block._find_var_recursive(n)
            if v is not None and getattr(v, "persistable", False):
                params.append(n)
            else:
                # a float activation used inside a stage but not declared
                # via side() would ride the (mixed-dtype, undifferentiated,
                # un-microbatched) Consts slot: silent wrong gradients.
                raise ValueError(
                    "float variable %r is read inside a pipeline stage but "
                    "is neither a parameter nor declared with pipe.side(); "
                    "register it as a side input (per-microbatch) or "
                    "compute it inside the stage" % n)

        # float and int sides ride separate slots so the generic vjp can
        # differentiate the float ones (e.g. enc_out feeding a decoder
        # region) — a mixed slot would be skipped wholesale
        f_sides = [v for v in self._sides
                   if v.dtype is not None and dtype_is_floating(v.dtype)]
        i_sides = [v for v in self._sides if v not in f_sides]

        parent = self.parent_block
        out = parent.create_var(
            name=unique_name.generate(self._carry_init.name + "@pipe_out"),
            shape=tuple(self._carry_init.shape),
            dtype=self._carry_init.dtype)
        parent.append_op(
            type="pipeline_region",
            inputs={
                "Carry": [self._carry_init.name],
                "Sides": [v.name for v in f_sides],
                "IntSides": [v.name for v in i_sides],
                "Params": params,
                "Consts": consts,
            },
            outputs={"Out": [out.name]},
            attrs={
                "sub_block": self.sub_block.idx,
                "stages": stages,
                "microbatches": self.microbatches or 0,
                "stage_bounds": list(self._stage_bounds),
                "carry_in_names": list(self._carry_in),
                "carry_out_names": list(self._carry_out),
                "side_names": [v.name for v in f_sides],
                "int_side_names": [v.name for v in i_sides],
                "param_names": params,
                "const_names": consts,
            })
        return out
