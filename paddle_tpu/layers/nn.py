"""Neural-network layers — the user-facing op DSL.

Parity: reference ``python/paddle/fluid/layers/nn.py`` (7k LoC, 123 public
fns).  This module covers the dense/MLP/classification core; conv/pool/norm
live in ``conv.py``, sequence layers in ``sequence.py``, control flow in
``control_flow.py``.  Layers append ops to the default main program and
create parameters via LayerHelper exactly like the reference.
"""

from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "fc",
    "embedding",
    "dropout",
    "softmax",
    "cross_entropy",
    "square_error_cost",
    "softmax_with_cross_entropy",
    "fused_attention",
    "paged_attention",
    "one_hot",
    "topk",
    "matmul",
    "mul",
    "label_smooth",
    "log",
    "relu",
    "l2_normalize",
    "prelu",
    "maxout",
    "cos_sim",
    "sampling_id",
    "smooth_l1",
    "margin_rank_loss",
    "clip",
    "clip_by_norm",
    "mean",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "nce",
    "hsigmoid",
    "bilinear_tensor_product",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "flatten",
    "sum",
    "multiplex",
    "rank_loss",
    "sigmoid_cross_entropy_with_logits",
    "gaussian_random",
    "mean_iou",
    "dice_loss",
    "image_resize_short",
    "lstm_unit",
    "gru_unit",
    "autoincreased_step_counter",
]


def fc(
    input,
    size,
    num_flatten_dims=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    is_test=False,
    name=None,
):
    """Fully-connected layer (reference nn.py:fc): per-input weight matmul
    (mul op), summed, plus bias and activation.  On TPU each mul is a single
    MXU gemm; multiple inputs become independent gemms XLA can batch."""
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()

    mul_results = []
    for input_var, p_attr in helper.iter_inputs_and_params():
        input_shape = input_var.shape
        param_num_flatten = num_flatten_dims
        w_rows = 1
        for s in input_shape[param_num_flatten:]:
            w_rows *= s
        param_shape = [w_rows, size]
        w = helper.create_parameter(attr=p_attr, shape=param_shape, dtype=dtype)
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="mul",
            inputs={"X": [input_var], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)

    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias]}
        )
    if helper.bias_attr and helper.kwargs.get("bias_attr") is not False:
        pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    else:
        pre_act = pre_bias
    return helper.append_activation(pre_act)


def embedding(
    input,
    size,
    is_sparse=False,
    is_distributed=False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
):
    """Embedding lookup (reference nn.py:embedding / lookup_table_op.cc).
    ``is_sparse`` selects the SelectedRows-style sparse-gradient path;
    ``is_distributed`` marks the table for mesh sharding (the pserver
    remote-prefetch equivalent — see parallel/embedding docs)."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(
        attr=helper.param_attr, shape=size, dtype=dtype, is_bias=False
    )
    tmp = helper.create_variable_for_type_inference(dtype)
    padding_idx = (
        -1 if padding_idx is None
        else padding_idx if padding_idx >= 0
        else (size[0] + padding_idx)
    )
    helper.append_op(
        type="lookup_table",
        inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [tmp]},
        attrs={"is_sparse": is_sparse, "is_distributed": is_distributed,
               "padding_idx": padding_idx},
    )
    return tmp


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    mask = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed if seed is not None else 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="softmax", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def square_error_cost(input, label):
    """Per-sample squared error (input - label)^2 (reference
    nn.py:1083 square_error_cost / squared_l2_distance_op.cc)."""
    helper = LayerHelper("square_error_cost", input=input)
    minus_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="elementwise_sub",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [minus_out]},
        attrs={"axis": -1},
    )
    square_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="square",
        inputs={"X": [minus_out]},
        outputs={"Out": [square_out]},
    )
    return square_out


def softmax_with_cross_entropy(
    logits, label, soft_label=False, ignore_index=-100,
    numeric_stable_mode=True, return_softmax=False, label_smooth_eps=0.0,
):
    """``label_smooth_eps`` is a TPU-side extension: uniform label smoothing
    fused into the loss kernel (loss = (1-eps)*nll + eps*(lse - mean logits))
    so the [N, C] one-hot/soft-label tensor the reference materializes
    (one_hot + label_smooth + soft_label CE) never exists in HBM."""
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(dtype=logits.dtype)
    loss = helper.create_variable_for_type_inference(dtype=logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax_out], "Loss": [loss]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index,
               "label_smooth_eps": float(label_smooth_eps)},
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def fused_attention(q, k, v, k_len=None, causal=False, dropout_rate=0.0,
                    is_test=False, scale=None, name=None):
    """Flash attention over head-split tensors q/k/v [B, H, T, D].

    ``k_len`` [B] int masks padded key positions; ``causal`` adds the
    autoregressive mask.  Never materializes the [B, H, Tq, Tk] score
    matrix (reference ``nets.scaled_dot_product_attention`` does); runs
    the Pallas kernel under FLAGS_pallas_kernels, an XLA fallback with
    identical semantics otherwise."""
    helper = LayerHelper("fused_attention", name=name)
    out = helper.create_variable_for_type_inference(dtype=q.dtype)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if k_len is not None:
        inputs["KLen"] = [k_len]
    attrs = {"causal": causal, "dropout_rate": float(dropout_rate),
             "is_test": is_test}
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op(
        type="fused_attention", inputs=inputs, outputs={"Out": [out]},
        attrs=attrs,
    )
    return out


def paged_attention(q, k_cache, v_cache, page_table, k_len=None,
                    k_scale=None, v_scale=None, causal=True, scale=None,
                    name=None):
    """Attention over a block-indexed KV pool (serving's paged cache).

    ``q`` [S, H, Tq, D] attends the pages ``page_table`` [S, max_pages]
    maps for each slot out of the shared pool ``k_cache``/``v_cache``
    [P, H, page_size, D]; ``k_len`` [S] is each slot's valid length
    (entries past it — including stale speculative tokens — are
    masked).  int8 pools dequantize through ``k_scale``/``v_scale``
    [P, H, page_size].  Causal ``Tq > 1`` is the bottom-aligned
    suffix-query shape speculative verify uses."""
    helper = LayerHelper("paged_attention", name=name)
    out = helper.create_variable_for_type_inference(dtype=q.dtype)
    inputs = {"Q": [q], "KCache": [k_cache], "VCache": [v_cache],
              "PageTable": [page_table]}
    if k_len is not None:
        inputs["KLen"] = [k_len]
    if k_scale is not None:
        inputs["KScale"] = [k_scale]
        inputs["VScale"] = [v_scale]
    attrs = {"causal": causal}
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op(
        type="paged_attention", inputs=inputs, outputs={"Out": [out]},
        attrs=attrs,
    )
    return out


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(
        type="one_hot", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"depth": depth},
    )
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(dtype=input.dtype)
    indices = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="top_k", inputs={"X": [input]},
        outputs={"Out": [values], "Indices": [indices]}, attrs={"k": k},
    )
    values.stop_gradient = True
    indices.stop_gradient = True
    return values, indices


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="matmul", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y,
               "alpha": float(alpha)},
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="mul", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]},
        attrs={"x_num_col_dims": x_num_col_dims,
               "y_num_col_dims": y_num_col_dims},
    )
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(
        type="label_smooth", inputs=inputs, outputs={"Out": [out]},
        attrs={"epsilon": float(epsilon)},
    )
    return out


def _unary_layer(op_type):
    def layer(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]})
        return out

    layer.__name__ = op_type
    return layer


log = _unary_layer("log")
relu = _unary_layer("relu")


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def _elementwise_layer(op_type):
    def layer(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, name=name, act=act)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(
            type=op_type, inputs={"X": [x], "Y": [y]},
            outputs={"Out": [out]}, attrs={"axis": axis},
        )
        return helper.append_activation(out)

    layer.__name__ = op_type
    return layer


elementwise_add = _elementwise_layer("elementwise_add")
elementwise_sub = _elementwise_layer("elementwise_sub")
elementwise_mul = _elementwise_layer("elementwise_mul")
elementwise_div = _elementwise_layer("elementwise_div")


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    """x / sqrt(sum(x^2, axis)) (reference nn.py:l2_normalize)."""
    from . import tensor as tensor_layers

    helper = LayerHelper("l2_normalize", name=name)
    sq = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="square", inputs={"X": [x]}, outputs={"Out": [sq]})
    ssum = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="reduce_sum", inputs={"X": [sq]}, outputs={"Out": [ssum]},
        attrs={"dim": [axis], "keep_dim": True, "reduce_all": False},
    )
    norm = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="clip", inputs={"X": [ssum]}, outputs={"Out": [norm]},
        attrs={"min": epsilon, "max": 3.4e38},
    )
    rsq = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="sqrt", inputs={"X": [norm]}, outputs={"Out": [rsq]})
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="elementwise_div", inputs={"X": [x], "Y": [rsq]},
        outputs={"Out": [out]}, attrs={"axis": 0},
    )
    return out


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name, param_attr=param_attr)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    elif mode == "element":
        alpha_shape = [int(_prod(x.shape[1:]))]
    else:
        raise ValueError("mode must be all|channel|element")
    from ..initializer import ConstantInitializer

    alpha = helper.create_parameter(
        attr=helper.param_attr, shape=alpha_shape, dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25),
    )
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="prelu", inputs={"X": [x], "Alpha": [alpha]},
        outputs={"Out": [out]}, attrs={"mode": mode},
    )
    return out


def _prod(xs):
    p = 1
    for x in xs:
        p *= x
    return p


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="maxout", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"groups": groups},
    )
    return out


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim")
    out = helper.create_variable_for_type_inference(dtype=X.dtype)
    xnorm = helper.create_variable_for_type_inference(dtype=X.dtype)
    ynorm = helper.create_variable_for_type_inference(dtype=X.dtype)
    helper.append_op(
        type="cos_sim", inputs={"X": [X], "Y": [Y]},
        outputs={"Out": [out], "XNorm": [xnorm], "YNorm": [ynorm]},
    )
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="sampling_id", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"min": min, "max": max, "seed": seed},
    )
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    diff = helper.create_variable_for_type_inference(dtype=x.dtype)
    loss = helper.create_variable_for_type_inference(dtype=x.dtype)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(
        type="smooth_l1_loss", inputs=inputs,
        outputs={"Diff": [diff], "Out": [loss]},
        attrs={"sigma": sigma if sigma is not None else 1.0},
    )
    return loss


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    """Pairwise hinge max(0, -label*(left-right) + margin) (reference
    margin_rank_loss_op.cc / nn.py margin_rank_loss; label is +-1)."""
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(dtype=left.dtype)
    act = helper.create_variable_for_type_inference(dtype=left.dtype)
    helper.append_op(
        type="margin_rank_loss",
        inputs={"Label": [label], "X1": [left], "X2": [right]},
        outputs={"Out": [out], "Activated": [act]},
        attrs={"margin": float(margin)},
    )
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="clip", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"min": float(min), "max": float(max)},
    )
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="clip_by_norm", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"max_norm": float(max_norm)},
    )
    return out


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None):
    """Noise-contrastive estimation loss (reference nn.py:3968 /
    nce_op.cc): per-sample cost [B, 1] over the true classes plus
    ``num_neg_samples`` uniform negatives."""
    helper = LayerHelper("nce", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = input.shape[-1]
    num_true = label.shape[-1] if len(label.shape) > 1 else 1
    num_neg = int(num_neg_samples) if num_neg_samples else 10
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[num_total_classes, dim],
                                dtype=input.dtype)
    inputs = {"Input": [input], "Label": [label], "Weight": [w]}
    if helper.kwargs.get("bias_attr") is not False:
        b = helper.create_parameter(attr=helper.bias_attr,
                                    shape=[num_total_classes, 1],
                                    dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight]
    cost = helper.create_variable_for_type_inference(input.dtype)
    logits = helper.create_variable_for_type_inference(input.dtype)
    labels_out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="nce", inputs=inputs,
        outputs={"Cost": [cost], "SampleLogits": [logits],
                 "SampleLabels": [labels_out]},
        attrs={"num_total_classes": int(num_total_classes),
               "num_neg_samples": num_neg, "num_true": int(num_true)})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """Hierarchical sigmoid loss over a complete binary tree (reference
    nn.py:4065 / hierarchical_sigmoid_op.cc): per-sample cost [B, 1]."""
    helper = LayerHelper("hsigmoid", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[num_classes - 1, dim],
                                dtype=input.dtype)
    inputs = {"X": [input], "W": [w], "Label": [label]}
    if helper.kwargs.get("bias_attr") is not False:
        b = helper.create_parameter(attr=helper.bias_attr,
                                    shape=[num_classes - 1, 1],
                                    dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="hierarchical_sigmoid", inputs=inputs,
        outputs={"Out": [out]},
        attrs={"num_classes": int(num_classes)})
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """out_k = x . W_k . y (reference bilinear_tensor_product_op.cc)."""
    helper = LayerHelper("bilinear_tensor_product", input=x,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dx, dy = x.shape[-1], y.shape[-1]
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[size, dx, dy], dtype=x.dtype)
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if helper.kwargs.get("bias_attr") is not False:
        b = helper.create_parameter(attr=helper.bias_attr, shape=[1, size],
                                    dtype=x.dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out)


# ---------------------------------------------------------------------------
# parity tail: the reference nn.py names not covered above
# ---------------------------------------------------------------------------

elementwise_max = _elementwise_layer("elementwise_max")
elementwise_min = _elementwise_layer("elementwise_min")
elementwise_pow = _elementwise_layer("elementwise_pow")


def flatten(x, axis=1, name=None):
    """Collapse dims before/after ``axis`` into a 2-D matrix (reference
    nn.py:6181 / flatten_op.cc)."""
    helper = LayerHelper("flatten", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="flatten", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": int(axis)})
    return out


def sum(x):
    """Elementwise sum of a list of tensors (reference nn.py:6630 /
    sum_op.cc; dense path — SelectedRows inputs ride ops/selected_rows)."""
    if isinstance(x, Variable):
        x = [x]
    helper = LayerHelper("sum")
    out = helper.create_variable_for_type_inference(dtype=x[0].dtype)
    helper.append_op(type="sum", inputs={"X": [v for v in x]},
                     outputs={"Out": [out]})
    return out


def multiplex(inputs, index):
    """Row-wise select among candidate tensors by index (reference
    nn.py:4353 / multiplex_op.cc)."""
    if not isinstance(inputs, (list, tuple)) or len(inputs) < 2:
        raise ValueError("multiplex needs at least 2 candidate tensors")
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(dtype=inputs[0].dtype)
    helper.append_op(
        type="multiplex",
        inputs={"X": [v for v in inputs], "Ids": [index]},
        outputs={"Out": [out]})
    return out


def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (reference nn.py:5759 / rank_loss_op.cc)."""
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_variable_for_type_inference(dtype=left.dtype)
    helper.append_op(
        type="rank_loss",
        inputs={"Label": [label], "Left": [left], "Right": [right]},
        outputs={"Out": [out]})
    return out


def sigmoid_cross_entropy_with_logits(x, label, name=None):
    """Per-element binary CE on logits (reference nn.py:7030 /
    sigmoid_cross_entropy_with_logits_op.cc)."""
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    """Normal-random tensor (reference nn.py:6519 / gaussian_random_op.cc;
    randomness rides the executor's counter PRNG, ``seed`` kept for API
    parity)."""
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="gaussian_random", inputs={}, outputs={"Out": [out]},
        attrs={"shape": list(shape), "mean": float(mean),
               "std": float(std), "seed": int(seed), "dtype": dtype})
    return out


def mean_iou(input, label, num_classes):
    """Mean intersection-over-union metric (reference nn.py:5611 /
    mean_iou_op.cc).  Returns (mean_iou, out_wrong, out_correct)."""
    helper = LayerHelper("mean_iou")
    iou = helper.create_variable_for_type_inference(dtype="float32")
    wrong = helper.create_variable_for_type_inference(dtype="int32")
    correct = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(
        type="mean_iou",
        inputs={"Predictions": [input], "Labels": [label]},
        outputs={"OutMeanIou": [iou], "OutWrong": [wrong],
                 "OutCorrect": [correct]},
        attrs={"num_classes": int(num_classes)})
    return iou, wrong, correct


def dice_loss(input, label, epsilon=1e-5):
    """Dice loss for binary segmentation (reference nn.py:5180): built
    from one_hot + reductions exactly as the reference composes it."""
    from . import tensor as tensor_layers
    from .ops import scale as scale_layer
    label = one_hot(label, depth=input.shape[-1])
    reduce_dim = list(range(1, len(input.shape)))
    inse = tensor_layers.reduce_sum(elementwise_mul(input, label),
                                    dim=reduce_dim)
    denom = elementwise_add(
        tensor_layers.reduce_sum(input, dim=reduce_dim),
        tensor_layers.reduce_sum(label, dim=reduce_dim))
    one = tensor_layers.fill_constant(shape=[1], dtype=input.dtype, value=1.0)
    score = elementwise_sub(
        one, elementwise_div(
            scale_layer(inse, scale=2.0),
            elementwise_add(denom, tensor_layers.fill_constant(
                shape=[1], dtype=input.dtype, value=float(epsilon)))))
    return tensor_layers.reduce_mean(score)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the SHORT image side equals ``out_short_len``, keeping
    aspect ratio (reference nn.py:5323)."""
    from .cnn import image_resize
    in_shape = input.shape
    if len(in_shape) != 4:
        raise ValueError("image_resize_short expects NCHW input")
    hw = in_shape[2:4]
    short_idx = hw.index(min(hw))
    out_shape = list(hw)
    out_shape[short_idx] = int(out_short_len)
    out_shape[1 - short_idx] = int(
        round(float(hw[1 - short_idx]) / hw[short_idx] * out_short_len))
    return image_resize(input, out_shape=out_shape, resample=resample)


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One LSTM step: fc([x_t, h_prev]) -> 4 gates -> lstm_unit op
    (reference nn.py:3008 / lstm_unit_op.cc).  Returns (hidden, cell)."""
    if len(x_t.shape) != 2 or len(hidden_t_prev.shape) != 2 or \
            len(cell_t_prev.shape) != 2:
        raise ValueError("lstm_unit takes 2-D x_t/hidden/cell")
    from .tensor import concat
    size = int(cell_t_prev.shape[1])
    concat_in = concat([x_t, hidden_t_prev], axis=1)
    fc_out = fc(concat_in, size=4 * size, param_attr=param_attr,
                bias_attr=bias_attr, name=name)
    helper = LayerHelper("lstm_unit", name=name)
    h = helper.create_variable_for_type_inference(dtype=x_t.dtype)
    c = helper.create_variable_for_type_inference(dtype=x_t.dtype)
    helper.append_op(
        type="lstm_unit",
        inputs={"X": [fc_out], "C_prev": [cell_t_prev]},
        outputs={"H": [h], "C": [c]},
        attrs={"forget_bias": float(forget_bias)})
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    """One GRU step over a pre-projected input (reference nn.py:751 /
    gru_unit_op.cc: ``input`` is the fc-transformed x, ``size`` = 3x the
    hidden dim).  Returns (hidden, reset_hidden_prev, gate)."""
    h_dim = size // 3
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[h_dim, 3 * h_dim],
                                dtype=input.dtype)
    inputs = {"Input": [input], "HiddenPrev": [hidden], "Weight": [w]}
    if helper.kwargs.get("bias_attr") is not False:
        b = helper.create_parameter(attr=helper.bias_attr,
                                    shape=[1, 3 * h_dim],
                                    dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    h = helper.create_variable_for_type_inference(dtype=input.dtype)
    gate = helper.create_variable_for_type_inference(dtype=input.dtype)
    rhp = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="gru_unit", inputs=inputs,
        outputs={"Hidden": [h], "Gate": [gate], "ResetHiddenPrev": [rhp]},
        attrs={"activation": activation,
               "gate_activation": gate_activation})
    return h, rhp, gate


def autoincreased_step_counter(counter_name=None, begin=1, step=1,
                               dtype="int64"):
    """A persistable counter advanced once per executed step (reference
    nn.py:4541).  The LR schedulers' ``_decay_step_counter`` delegates
    here — one counter builder, two callers."""
    helper = LayerHelper("step_counter")
    block = helper.main_program.global_block()
    name = counter_name or "@STEP_COUNTER@"
    counter = block._find_var_recursive(name)
    if counter is None:
        counter = block.create_var(name=name, shape=(1,), dtype=dtype,
                                   persistable=True)
        startup_blk = helper.startup_program.global_block()
        startup_blk.create_var(name=name, shape=(1,), dtype=dtype,
                               persistable=True)
        from ..initializer import Constant
        Constant(value=float(begin - step))(counter, startup_blk)
        helper.append_op(
            type="increment", inputs={"X": [counter]},
            outputs={"Out": [counter]}, attrs={"step": float(step)})
        counter.stop_gradient = True
    return counter
