"""Layer-wrapper generation utilities (reference
``layers/layer_function_generator.py:1``: generates Python layer fns
from OpProto metadata; here they generate from the op registry — same
idea, no proto)."""

from ..layer_helper import LayerHelper
from ..registry import OPS

__all__ = ["deprecated", "generate_layer_fn", "generate_layer_fn_noattr",
           "autodoc", "templatedoc"]


def _op_doc(op_type):
    op = OPS.get(op_type)
    return (op.doc if op is not None and op.doc else
            "%s layer (generated from the op registry)" % op_type)


def deprecated(since, instead, extra_message=""):
    """Decorator stamping a deprecation notice into the docstring and
    warning once per call site (reference annotations.deprecated)."""
    from ..annotations import deprecated as _dep
    return _dep(since, instead, extra_message)


def generate_layer_fn(op_type):
    """A layer fn for a registered single-output op: positional tensor
    inputs in registry order, attrs as keywords (reference
    layer_function_generator.py generate_layer_fn)."""
    op = OPS.get(op_type)
    if op is None:
        raise ValueError("op %r is not registered" % op_type)
    in_slots = [s for s in op.input_slots if not s.startswith("GRAD::")]
    out_slots = [s for s in op.output_slots if not s.startswith("GRAD::")]

    def layer(*args, **kwargs):
        name = kwargs.pop("name", None)
        act = kwargs.pop("act", None)
        helper = LayerHelper(op_type, name=name, act=act)
        inputs = {}
        for slot, arg in zip(in_slots, args):
            inputs[slot] = arg if isinstance(arg, (list, tuple)) else [arg]
        for slot in in_slots[len(args):]:
            if slot in kwargs:
                arg = kwargs.pop(slot)
                inputs[slot] = arg if isinstance(arg, (list, tuple)) \
                    else [arg]
        dtype = None
        for vs in inputs.values():
            for v in vs:
                if getattr(v, "dtype", None) is not None:
                    dtype = v.dtype
                    break
            if dtype is not None:
                break
        outs = {s: [helper.create_variable_for_type_inference(dtype=dtype)]
                for s in out_slots}
        helper.append_op(type=op_type, inputs=inputs, outputs=outs,
                         attrs=kwargs)
        result = [outs[s][0] for s in out_slots]
        first = helper.append_activation(result[0])
        return first if len(result) == 1 else (first, *result[1:])

    layer.__name__ = op_type
    layer.__doc__ = _op_doc(op_type)
    return layer


def generate_layer_fn_noattr(op_type):
    """Single-input single-output attr-less wrapper (reference
    generate_layer_fn_noattr — the activation-op fast path)."""
    def layer(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]})
        return out

    layer.__name__ = op_type
    layer.__doc__ = _op_doc(op_type)
    return layer


def autodoc(comment=""):
    """Replace the decorated fn's docstring with the registry doc of the
    same-named op plus ``comment`` (reference autodoc)."""
    def decorator(func):
        func.__doc__ = comment + _op_doc(func.__name__)
        return func
    return decorator


def templatedoc(op_type=None):
    """Format ``${comment}`` placeholders in the decorated fn's
    docstring from the registry doc (reference templatedoc)."""
    def decorator(func):
        doc = func.__doc__ or ""
        comment = _op_doc(op_type or func.__name__)
        func.__doc__ = doc.replace("${comment}", comment)
        return func
    return decorator
