"""Layer DSL (reference ``python/paddle/fluid/layers/``)."""

from .. import ops as _ops  # noqa: F401 — register op library first

from . import cnn, control_flow, detection, io, learning_rate_scheduler, \
    layer_function_generator, math_op_patch, metric_op, nn, ops, pipeline, \
    sequence, tensor  # noqa: F401
from .cnn import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from .learning_rate_scheduler import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from .io import *  # noqa: F401,F403
from .nn import *  # noqa: F401,F403
from .pipeline import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .metric_op import *  # noqa: F401,F403
from .layer_function_generator import *  # noqa: F401,F403

math_op_patch.monkey_patch_variable()

__all__ = (
    cnn.__all__ + control_flow.__all__ + detection.__all__ + io.__all__
    + learning_rate_scheduler.__all__ + sequence.__all__ + nn.__all__
    + ops.__all__ + pipeline.__all__ + tensor.__all__ + metric_op.__all__
    + layer_function_generator.__all__
)
