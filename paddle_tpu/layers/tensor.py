"""Tensor-creation and manipulation layers.

Parity: reference ``python/paddle/fluid/layers/tensor.py`` (692 LoC):
create_tensor, fill_constant, cast, concat, sums, assign, argmin/argmax,
ones, zeros, reverse...
"""

import numpy as np

from ..core import convert_dtype
from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "create_tensor",
    "create_global_var",
    "cast",
    "concat",
    "sums",
    "assign",
    "fill_constant",
    "fill_constant_batch_size_like",
    "argmin",
    "argmax",
    "argsort",
    "ones",
    "zeros",
    "reverse",
    "reshape",
    "squeeze",
    "unsqueeze",
    "transpose",
    "split",
    "stack",
    "expand",
    "slice",
    "shape",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "gather",
    "scatter",
    "pad",
    "pad2d",
    "pad_constant_like",
    "crop",
    "random_crop",
    "unstack",
    "uniform_random_batch_size_like",
    "gaussian_random_batch_size_like",
    "cumsum",
    "increment",
    "isfinite",
    "has_inf",
    "has_nan",
    "create_parameter",
    "less_than",
    "equal",
    "less_equal",
    "greater_than",
    "greater_equal",
    "not_equal",
    "logical_and",
    "logical_or",
    "logical_xor",
    "logical_not",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_global_variable(
        name=helper.name + ".tensor", dtype=dtype, persistable=persistable
    )


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Create a bare learnable parameter (reference tensor.py:58 — the
    low-level API for hand-built operator graphs)."""
    from ..param_attr import ParamAttr
    helper = LayerHelper("create_parameter")
    if attr is None:
        attr = ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        name=name or helper.name, shape=shape, dtype=dtype,
        persistable=persistable,
    )
    from ..initializer import ConstantInitializer

    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="cast", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"out_dtype": str(convert_dtype(dtype))},
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op(
        type="concat", inputs={"X": input}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op(type="sum", inputs={"X": input}, outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(dtype=input.dtype)
        helper.append_op(
            type="assign", inputs={"X": [input]}, outputs={"Out": [output]}
        )
    elif isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_variable_for_type_inference(
                dtype=str(input.dtype))
        helper.append_op(
            type="assign_value",
            outputs={"Out": [output]},
            attrs={"shape": list(input.shape), "dtype": str(input.dtype),
                   "values": input.reshape(-1).tolist()},
        )
    else:
        raise TypeError("assign accepts Variable or numpy array")
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": str(convert_dtype(dtype)),
               "value": float(value)},
    )
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": str(convert_dtype(dtype)),
               "value": float(value), "input_dim_idx": input_dim_idx,
               "output_dim_idx": output_dim_idx},
    )
    out.stop_gradient = True
    return out


def _arg_layer(op_type):
    def layer(x, axis=0):
        helper = LayerHelper(op_type)
        out = helper.create_variable_for_type_inference(dtype="int64")
        helper.append_op(
            type=op_type, inputs={"X": [x]}, outputs={"Out": [out]},
            attrs={"axis": axis},
        )
        return out

    return layer


argmin = _arg_layer("arg_min")
argmax = _arg_layer("arg_max")


def argsort(input, axis=-1, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    ids = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="argsort", inputs={"X": [input]},
        outputs={"Out": [out], "Indices": [ids]}, attrs={"axis": axis},
    )
    return out, ids


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=0.0)


def reverse(x, axis):
    if isinstance(axis, int):
        axis = [axis]
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="reverse", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape", name=name, act=act)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="reshape", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"shape": list(shape)},
    )
    return helper.append_activation(out)


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="squeeze", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"axes": list(axes)},
    )
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="unsqueeze", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"axes": list(axes)},
    )
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="transpose", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": list(perm)},
    )
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
    else:
        num = len(num_or_sections)
        sections = list(num_or_sections)
    outs = [
        helper.create_variable_for_type_inference(dtype=input.dtype)
        for _ in range(num)
    ]
    helper.append_op(
        type="split", inputs={"X": [input]}, outputs={"Out": outs},
        attrs={"axis": dim, "sections": sections, "num": num},
    )
    return outs


def stack(x, axis=0):
    if not isinstance(x, (list, tuple)):
        x = [x]
    helper = LayerHelper("stack")
    out = helper.create_variable_for_type_inference(dtype=x[0].dtype)
    helper.append_op(
        type="stack", inputs={"X": x}, outputs={"Y": [out]},
        attrs={"axis": axis},
    )
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="expand", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"expand_times": list(expand_times)},
    )
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="slice", inputs={"Input": [input]}, outputs={"Out": [out]},
        attrs={"axes": list(axes), "starts": list(starts),
               "ends": list(ends)},
    )
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="shape", inputs={"Input": [input]}, outputs={"Out": [out]}
    )
    return out


def _reduce_layer(op_type):
    def layer(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(dtype=input.dtype)
        if dim is None:
            attrs = {"dim": [0], "keep_dim": keep_dim, "reduce_all": True}
        else:
            if isinstance(dim, int):
                dim = [dim]
            attrs = {"dim": list(dim), "keep_dim": keep_dim,
                     "reduce_all": False}
        helper.append_op(
            type=op_type, inputs={"X": [input]}, outputs={"Out": [out]},
            attrs=attrs,
        )
        return out

    layer.__name__ = op_type
    return layer


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")


def gather(input, index):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="gather", inputs={"X": [input], "Index": [index]},
        outputs={"Out": [out]},
    )
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]}, attrs={"overwrite": overwrite},
    )
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="pad", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "pad_value": float(pad_value)},
    )
    return out


def cumsum(x, axis=-1, exclusive=False, reverse=False):
    helper = LayerHelper("cumsum")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="cumsum", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": axis, "exclusive": exclusive, "reverse": reverse},
    )
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(
        dtype=x.dtype)
    helper.append_op(
        type="increment", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"step": float(value)},
    )
    return out


def isfinite(x):
    helper = LayerHelper("isfinite")
    out = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(
        type="isfinite", inputs={"X": [x]}, outputs={"Out": [out]}
    )
    return out


def _cmp_layer(op_type):
    def layer(x, y, cond=None):
        helper = LayerHelper(op_type)
        if cond is None:
            cond = helper.create_variable_for_type_inference(dtype="bool")
        helper.append_op(
            type=op_type, inputs={"X": [x], "Y": [y]},
            outputs={"Out": [cond]},
        )
        return cond

    return layer


less_than = _cmp_layer("less_than")
equal = _cmp_layer("equal")
less_equal = _cmp_layer("less_equal")
greater_than = _cmp_layer("greater_than")
greater_equal = _cmp_layer("greater_equal")
not_equal = _cmp_layer("not_equal")


def _logical_layer(op_type, binary=True):
    def layer(x, y=None, out=None, name=None):
        helper = LayerHelper(op_type, name=name)
        if out is None:
            out = helper.create_variable_for_type_inference(dtype="bool")
        inputs = {"X": [x]}
        if binary:
            inputs["Y"] = [y]
        helper.append_op(type=op_type, inputs=inputs,
                         outputs={"Out": [out]})
        return out

    return layer


logical_and = _logical_layer("logical_and")
logical_or = _logical_layer("logical_or")
logical_xor = _logical_layer("logical_xor")
logical_not = _logical_layer("logical_not", binary=False)


def crop(x, shape=None, offsets=None, name=None):
    """Crop ``x`` to ``shape`` at ``offsets`` (reference nn.py:crop /
    crop_op.cc).  ``shape``/``offsets`` may be lists or Variables."""
    helper = LayerHelper("crop", name=name)
    inputs = {"X": [x]}
    attrs = {}
    if isinstance(shape, Variable):
        inputs["Y"] = [shape]
    elif shape is not None:
        attrs["shape"] = list(shape)
    if isinstance(offsets, Variable):
        inputs["Offsets"] = [offsets]
    elif offsets is not None:
        attrs["offsets"] = list(offsets)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="crop", inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    """Pad images [top, bottom, left, right] in constant/reflect/edge mode
    (reference nn.py:pad2d / pad2d_op.cc)."""
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="pad2d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "mode": mode,
               "pad_value": float(pad_value), "data_format": data_format},
    )
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Pad ``y`` up to the shape of ``x`` (reference nn.py:pad_constant_like
    / pad_constant_like_op.cc)."""
    helper = LayerHelper("pad_constant_like", name=name)
    out = helper.create_variable_for_type_inference(dtype=y.dtype)
    helper.append_op(
        type="pad_constant_like", inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]}, attrs={"pad_value": float(pad_value)},
    )
    return out


def random_crop(x, shape, seed=None):
    """Per-instance random crop to ``shape`` (reference nn.py:random_crop /
    random_crop_op.cc).  ``seed`` is accepted for API parity; randomness
    comes from the executor's counter PRNG."""
    helper = LayerHelper("random_crop")
    inputs = {"X": [x]}
    outputs = {"Out": [helper.create_variable_for_type_inference(x.dtype)]}
    if isinstance(seed, Variable):
        inputs["Seed"] = [seed]
        outputs["SeedOut"] = [
            helper.create_variable_for_type_inference("int64")]
    startup = seed if isinstance(seed, int) else 0
    helper.append_op(type="random_crop", inputs=inputs, outputs=outputs,
                     attrs={"shape": list(shape), "startup_seed": startup})
    return outputs["Out"][0]


def unstack(x, axis=0, num=None):
    """Unstack ``x`` into ``num`` tensors along ``axis`` (reference
    nn.py:unstack / unstack_op.h)."""
    helper = LayerHelper("unstack")
    if num is None:
        num = x.shape[axis]
    if num is None or num < 0:
        raise ValueError(
            "unstack: dim %d of %r is dynamic; pass num= explicitly"
            % (axis, x.name))
    outs = [helper.create_variable_for_type_inference(x.dtype)
            for _ in range(num)]
    helper.append_op(type="unstack", inputs={"X": [x]},
                     outputs={"Y": outs},
                     attrs={"axis": axis, "num": num})
    return outs


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    """Uniform random tensor whose batch dim copies ``input``'s (reference
    nn.py:uniform_random_batch_size_like)."""
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="uniform_random_batch_size_like", inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "input_dim_idx": input_dim_idx,
               "output_dim_idx": output_dim_idx, "min": float(min),
               "max": float(max), "seed": seed, "dtype": dtype},
    )
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    """Gaussian random tensor whose batch dim copies ``input``'s (reference
    nn.py:gaussian_random_batch_size_like)."""
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="gaussian_random_batch_size_like", inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "input_dim_idx": input_dim_idx,
               "output_dim_idx": output_dim_idx, "mean": float(mean),
               "std": float(std), "seed": seed, "dtype": dtype},
    )
    return out


def has_inf(x):
    """Any-element-is-inf scalar bool (reference tensor.py:646)."""
    helper = LayerHelper("has_inf")
    out = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="has_inf", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def has_nan(x):
    """Any-element-is-nan scalar bool (reference tensor.py:662)."""
    helper = LayerHelper("has_nan")
    out = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="has_nan", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out
