"""Sequence & recurrent layers over padded batches.

Parity: reference ``python/paddle/fluid/layers/nn.py`` dynamic_lstm,
dynamic_lstmp, dynamic_gru, sequence_conv, sequence_pool(+first/last
step), sequence_softmax, sequence_expand, sequence_reverse, row_conv,
sequence_mask, sequence_concat, sequence_erase, sequence_enumerate,
sequence_slice — the LoD input contract becomes the padded-batch +
``<name>@LEN`` companion convention (see ops/sequence.py).  Lengths
propagate through ops automatically (framework.Block._infer_and_mark);
every wrapper also accepts an explicit ``length=`` Variable.
"""

from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "dynamic_lstm",
    "dynamic_lstmp",
    "dynamic_gru",
    "sequence_conv",
    "sequence_pool",
    "sequence_first_step",
    "sequence_last_step",
    "sequence_softmax",
    "sequence_expand",
    "sequence_reverse",
    "sequence_mask",
    "sequence_concat",
    "sequence_erase",
    "sequence_enumerate",
    "sequence_length",
    "causal_mask",
    "padding_attn_bias",
    "padding_mask",
    "row_conv",
    "linear_chain_crf",
    "crf_decoding",
    "chunk_eval",
    "warpctc",
    "ctc_greedy_decoder",
    "edit_distance",
    "sequence_pad",
    "sequence_unpad",
    "sequence_reshape",
    "sequence_expand_as",
    "sequence_scatter",
    "im2sequence",
    "lod_reset",
]


def sequence_length(x, block=None):
    """The companion length Variable of a padded sequence var."""
    name = getattr(x, "_seq_len_name", None)
    if name is None:
        raise ValueError(
            "variable %r has no sequence-length companion; create it with "
            "layers.data(lod_level=1) or pass length= explicitly" % x.name)
    blk = block if block is not None else x.block
    return blk._find_var_recursive(name)


def _len_of(helper, x, length):
    if length is not None:
        return length
    return sequence_length(x)


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None,
                 length=None):
    """LSTM over a padded sequence batch; ``input`` is [B, T, 4*size]
    (pre-projected, reference nn.py:dynamic_lstm contract)."""
    helper = LayerHelper("dynamic_lstm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    size = size // 4 * 4
    h = size // 4
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[h, 4 * h], dtype=dtype)
    bias_size = [1, 7 * h if use_peepholes else 4 * h]
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=bias_size, dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias],
              "Length": [_len_of(helper, input, length)]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(
        type="lstm", inputs=inputs,
        outputs={"Hidden": [hidden], "Cell": [cell]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation})
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None, length=None):
    helper = LayerHelper("dynamic_lstmp", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    h = size // 4
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[proj_size, 4 * h], dtype=dtype)
    proj_weight = helper.create_parameter(
        attr=helper.param_attr, shape=[h, proj_size], dtype=dtype)
    bias_size = [1, 7 * h if use_peepholes else 4 * h]
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=bias_size, dtype=dtype, is_bias=True)
    proj = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="lstmp",
        inputs={"Input": [input], "Weight": [weight],
                "ProjWeight": [proj_weight], "Bias": [bias],
                "Length": [_len_of(helper, input, length)]},
        outputs={"Projection": [proj], "Cell": [cell]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation})
    return proj, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, name=None,
                length=None):
    """GRU over a padded batch; ``input`` is [B, T, 3*size]."""
    helper = LayerHelper("dynamic_gru", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = helper.input_dtype()
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[size, 3 * size], dtype=dtype)
    # bias folds into the pre-projected input for parity the reference adds
    # bias inside the op; we add it to input via elementwise_add
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[3 * size], dtype=dtype, is_bias=True)
    biased = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="elementwise_add", inputs={"X": [input], "Y": [bias]},
        outputs={"Out": [biased]}, attrs={"axis": 2})
    hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [biased], "Weight": [weight],
              "Length": [_len_of(helper, input, length)]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(
        type="gru", inputs=inputs, outputs={"Hidden": [hidden]},
        attrs={"is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "activation": candidate_activation})
    return hidden


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None,
                  name=None, length=None):
    helper = LayerHelper("sequence_conv", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    d = input.shape[-1]
    filter_shape = [filter_size * d, num_filters]
    w = helper.create_parameter(attr=helper.param_attr, shape=filter_shape,
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="sequence_conv",
        inputs={"X": [input], "Filter": [w],
                "Length": [_len_of(helper, input, length)]},
        outputs={"Out": [out]},
        attrs={"contextLength": filter_size,
               "contextStart": -((filter_size - 1) // 2),
               "contextStride": filter_stride})
    if helper.bias_attr is not None and \
            helper.kwargs.get("bias_attr") is not False:
        out = helper.append_bias_op(out, dim_start=2)
    # (the length companion propagates through bias/activation ops via
    # Block._infer_and_mark)
    return helper.append_activation(out)


def sequence_pool(input, pool_type, length=None):
    helper = LayerHelper("sequence_pool", input=input)
    dtype = helper.input_dtype()
    out = helper.create_variable_for_type_inference(dtype)
    max_index = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="sequence_pool",
        inputs={"X": [input], "Length": [_len_of(helper, input, length)]},
        outputs={"Out": [out], "MaxIndex": [max_index]},
        attrs={"pooltype": pool_type.upper()})
    out._seq_len_name = None  # pooled away the time axis
    return out


def sequence_first_step(input, length=None):
    return sequence_pool(input, "first", length)


def sequence_last_step(input, length=None):
    return sequence_pool(input, "last", length)


def sequence_softmax(input, use_cudnn=False, name=None, length=None):
    helper = LayerHelper("sequence_softmax", input=input, name=name)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(
        type="sequence_softmax",
        inputs={"X": [input], "Length": [_len_of(helper, input, length)]},
        outputs={"Out": [out]})
    return out


def sequence_expand(x, y, ref_level=-1, name=None, length=None):
    helper = LayerHelper("sequence_expand", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    ln = length if length is not None else sequence_length(y)
    helper.append_op(
        type="sequence_expand",
        inputs={"X": [x], "Y": [y], "Length": [ln]},
        outputs={"Out": [out]})
    out._seq_len_name = ln.name
    return out


def sequence_reverse(x, name=None, length=None):
    helper = LayerHelper("sequence_reverse", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_reverse",
        inputs={"X": [x], "Length": [_len_of(helper, x, length)]},
        outputs={"Out": [out]})
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """x: [batch] lengths -> [batch, maxlen] 0/1 mask."""
    if maxlen is None or (isinstance(maxlen, Variable)):
        raise ValueError("sequence_mask requires a static int maxlen on TPU")
    helper = LayerHelper("sequence_mask", input=x, name=name)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="sequence_mask", inputs={"X": [x]}, outputs={"Y": [out]},
        attrs={"maxlen": int(maxlen), "out_dtype": dtype})
    return out


def sequence_concat(input, name=None, lengths=None):
    helper = LayerHelper("sequence_concat", input=input, name=name)
    xs = list(input)
    lens = lengths or [sequence_length(v) for v in xs]
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    out_len = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="sequence_concat",
        inputs={"X": xs, "Length": lens},
        outputs={"Out": [out], "OutLength": [out_len]})
    out._seq_len_name = out_len.name
    return out


def sequence_erase(input, tokens, name=None, length=None):
    helper = LayerHelper("sequence_erase", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="sequence_erase",
        inputs={"X": [input], "Length": [_len_of(helper, input, length)]},
        outputs={"Out": [out], "OutLength": [out_len]},
        attrs={"tokens": list(tokens)})
    out._seq_len_name = out_len.name
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None, length=None):
    helper = LayerHelper("sequence_enumerate", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_enumerate",
        inputs={"X": [input], "Length": [_len_of(helper, input, length)]},
        outputs={"Out": [out]},
        attrs={"win_size": win_size, "pad_value": pad_value})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None, length=None):
    helper = LayerHelper("row_conv", input=input, param_attr=param_attr,
                         act=act, name=name)
    dtype = helper.input_dtype()
    filter_shape = [future_context_size + 1, input.shape[-1]]
    w = helper.create_parameter(attr=helper.param_attr, shape=filter_shape,
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="row_conv",
        inputs={"X": [input], "Filter": [w],
                "Length": [_len_of(helper, input, length)]},
        outputs={"Out": [out]})
    return helper.append_activation(out)


def causal_mask(ref=None, seq_len=-1, mask_value=-1e9, dtype="float32",
                name=None):
    """[T, T] additive causal bias (0 on/below diagonal, mask_value above)
    for decoder self-attention; T from ``ref``'s time axis (runtime pad
    length) or a static ``seq_len``. (Transformer support; no reference
    analog — the reference predates attention.)"""
    helper = LayerHelper("causal_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"Ref": [ref]} if ref is not None else {}
    helper.append_op(
        type="causal_mask", inputs=inputs, outputs={"Out": [out]},
        attrs={"seq_len": int(seq_len), "mask_value": float(mask_value),
               "dtype": dtype})
    out.stop_gradient = True
    out._seq_len_name = None
    return out


def padding_attn_bias(length, ref, mask_value=-1e9, dtype="float32",
                      name=None):
    """[B] lengths -> [B, 1, 1, T] additive attention bias, T from ``ref``."""
    helper = LayerHelper("padding_attn_bias", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="padding_attn_bias", inputs={"Length": [length], "Ref": [ref]},
        outputs={"Out": [out]},
        attrs={"mask_value": float(mask_value), "dtype": dtype})
    out.stop_gradient = True
    out._seq_len_name = None
    return out


def padding_mask(length, ref, dtype="float32", name=None):
    """[B] lengths -> [B, T] 0/1 mask, T from ``ref``'s time axis."""
    helper = LayerHelper("padding_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="padding_mask", inputs={"Length": [length], "Ref": [ref]},
        outputs={"Out": [out]}, attrs={"dtype": dtype})
    out.stop_gradient = True
    out._seq_len_name = None
    return out


def linear_chain_crf(input, label, param_attr=None, length=None):
    """Linear-chain CRF cost (reference nn.py:850 / linear_chain_crf_op.cc).

    ``input``: [B, T, D] padded emissions (lod_level=1 data or RNN/fc
    output); ``label``: [B, T, 1] int64 gold tags.  Creates the
    [D+2, D] transition parameter (rows: start, end, D tag->tag rows)
    and returns the per-sequence negative log-likelihood [B, 1] —
    ``mean()`` of it is the training cost, as in the reference's
    label_semantic_roles config.
    """
    helper = LayerHelper("linear_chain_crf", input=input,
                         param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[size + 2, size],
        dtype=helper.input_dtype())
    log_likelihood = helper.create_variable_for_type_inference(
        helper.input_dtype())
    helper.append_op(
        type="linear_chain_crf",
        inputs={"Emission": [input],
                "Length": [_len_of(helper, input, length)],
                "Transition": [transition], "Label": [label]},
        outputs={"LogLikelihood": [log_likelihood]})
    log_likelihood._seq_len_name = None
    return log_likelihood


def crf_decoding(input, param_attr=None, label=None, length=None):
    """Viterbi decode (reference crf_decoding_op.cc).  With ``label``,
    returns the per-position correctness mask instead of the path
    (crf_decoding_op.h:61)."""
    helper = LayerHelper("crf_decoding", input=input, param_attr=param_attr)
    # the transition parameter was created by linear_chain_crf under
    # param_attr.name — look it up rather than re-creating it
    transition = helper.main_program.global_block()._find_var_recursive(
        helper.param_attr.name)
    if transition is None:
        raise ValueError(
            "crf_decoding: transition parameter %r not found; pass the "
            "same param_attr used by linear_chain_crf"
            % helper.param_attr.name)
    path = helper.create_variable_for_type_inference("int64")
    inputs = {"Emission": [input],
              "Length": [_len_of(helper, input, length)],
              "Transition": [transition]}
    if label is not None:
        inputs["Label"] = [label]
    helper.append_op(type="crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [path]})
    path.stop_gradient = True
    path._seq_len_name = getattr(input, "_seq_len_name", None)
    return path


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, length=None):
    """Chunk precision/recall/F1 (reference chunk_eval_op.cc).  Returns
    (precision, recall, f1, num_infer, num_label, num_correct)."""
    helper = LayerHelper("chunk_eval", input=input)
    precision = helper.create_variable_for_type_inference("float32")
    recall = helper.create_variable_for_type_inference("float32")
    f1 = helper.create_variable_for_type_inference("float32")
    num_infer = helper.create_variable_for_type_inference("int64")
    num_label = helper.create_variable_for_type_inference("int64")
    num_correct = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="chunk_eval",
        inputs={"Inference": [input], "Label": [label],
                "Length": [_len_of(helper, input, length)]},
        outputs={"Precision": [precision], "Recall": [recall],
                 "F1-Score": [f1], "NumInferChunks": [num_infer],
                 "NumLabelChunks": [num_label],
                 "NumCorrectChunks": [num_correct]},
        attrs={"chunk_scheme": chunk_scheme,
               "num_chunk_types": int(num_chunk_types),
               "excluded_chunk_types": list(excluded_chunk_types or [])})
    for v in (precision, recall, f1, num_infer, num_label, num_correct):
        v.stop_gradient = True
        v._seq_len_name = None
    return precision, recall, f1, num_infer, num_label, num_correct


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    """CTC loss (reference warpctc_op.cc, nn.py warpctc): ``input`` is
    [B, T, num_classes+1] unscaled logits (padded sequence), ``label``
    [B, U, 1] int tokens.  Returns per-sequence loss [B, 1]."""
    helper = LayerHelper("warpctc", input=input)
    loss = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(
        type="warpctc",
        inputs={"Logits": [input],
                "LogitsLength": [_len_of(helper, input, input_length)],
                "Label": [label],
                "LabelLength": [_len_of(helper, label, label_length)]},
        outputs={"Loss": [loss]},
        attrs={"blank": int(blank), "norm_by_times": bool(norm_by_times)})
    loss._seq_len_name = None
    return loss


def ctc_greedy_decoder(input, blank, input_length=None, name=None):
    """Greedy CTC decode (reference nn.py ctc_greedy_decoder =
    argmax + ctc_align): merge repeated tokens, drop blanks."""
    helper = LayerHelper("ctc_greedy_decoder", input=input, name=name)
    # argmax over classes
    from .tensor import argmax  # local import to avoid cycles
    best = argmax(input, axis=-1)
    out = helper.create_variable_for_type_inference("int64")
    out_len = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="ctc_align",
        inputs={"Input": [best],
                "Length": [_len_of(helper, input, input_length)]},
        outputs={"Output": [out], "OutputLength": [out_len]},
        attrs={"blank": int(blank), "merge_repeated": True})
    out.stop_gradient = True
    out._seq_len_name = out_len.name
    return out


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Levenshtein distance per sequence pair (reference
    edit_distance_op.cc, nn.py edit_distance).  Returns (distance [B,1]
    float32, sequence_num [1] int64)."""
    helper = LayerHelper("edit_distance", input=input)
    if ignored_tokens:
        input = sequence_erase(input, tokens=list(ignored_tokens),
                               length=input_length)
        label = sequence_erase(label, tokens=list(ignored_tokens),
                               length=label_length)
        input_length = label_length = None
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="edit_distance",
        inputs={"Hyps": [input],
                "HypsLength": [_len_of(helper, input, input_length)],
                "Refs": [label],
                "RefsLength": [_len_of(helper, label, label_length)]},
        outputs={"Out": [out], "SequenceNum": [seq_num]},
        attrs={"normalized": bool(normalized)})
    out.stop_gradient = True
    seq_num.stop_gradient = True
    out._seq_len_name = None
    seq_num._seq_len_name = None
    return out, seq_num


def sequence_pad(x, pad_value=None, maxlen=None, name=None, length=None):
    """Pad a sequence batch to dense [B, T, ...] (reference
    sequence_pad_op.cc).  Returns (out, lengths[int64])."""
    helper = LayerHelper("sequence_pad", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    seq_len = helper.create_variable_for_type_inference("int64")
    inputs = {"X": [x], "Length": [_len_of(helper, x, length)]}
    if pad_value is not None:
        inputs["PadValue"] = [pad_value]
    helper.append_op(
        type="sequence_pad", inputs=inputs,
        outputs={"Out": [out], "SeqLength": [seq_len]},
        attrs={"padded_length": int(maxlen) if maxlen else -1})
    out._seq_len_name = None          # dense output
    seq_len.stop_gradient = True
    return out, seq_len


def sequence_unpad(x, length, name=None):
    """Dense [B, T, ...] + lengths -> sequence batch (reference
    sequence_unpad_op.cc)."""
    helper = LayerHelper("sequence_unpad", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out_len = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="sequence_unpad", inputs={"X": [x], "Length": [length]},
        outputs={"Out": [out], "OutLength": [out_len]})
    out._seq_len_name = out_len.name
    return out


def sequence_reshape(input, new_dim, length=None):
    """Re-chunk each sequence to rows of ``new_dim`` (reference
    sequence_reshape_op.cc)."""
    helper = LayerHelper("sequence_reshape", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="sequence_reshape",
        inputs={"X": [input], "Length": [_len_of(helper, input, length)]},
        outputs={"Out": [out], "OutLength": [out_len]},
        attrs={"new_dim": int(new_dim)})
    out._seq_len_name = out_len.name
    return out


def sequence_expand_as(x, y, name=None, y_length=None):
    """Repeat row i of ``x`` to y's sequence-i length (reference
    sequence_expand_as_op.cc)."""
    helper = LayerHelper("sequence_expand_as", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out_len = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="sequence_expand_as",
        inputs={"X": [x], "Y": [y],
                "YLength": [_len_of(helper, y, y_length)]},
        outputs={"Out": [out], "OutLength": [out_len]})
    out._seq_len_name = out_len.name
    return out


def sequence_scatter(input, index, updates, name=None, length=None):
    """Scatter-add update sequences into dense rows (reference
    sequence_scatter_op.cc)."""
    helper = LayerHelper("sequence_scatter", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates],
                "Length": [_len_of(helper, index, length)]},
        outputs={"Out": [out]})
    out._seq_len_name = None
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    """Image -> patch sequence (reference im2sequence_op.cc)."""
    helper = LayerHelper("im2sequence", input=input, name=name)
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding] * 4
    elif len(padding) == 2:
        padding = list(padding) * 2
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="im2sequence", inputs={"X": [input]},
        outputs={"Out": [out], "OutLength": [out_len]},
        attrs={"kernels": list(filter_size), "strides": list(stride),
               "paddings": list(padding)})
    out._seq_len_name = out_len.name
    return out


def lod_reset(x, y=None, target_lod=None):
    """Replace the sequence-length companion of ``x`` (reference
    nn.py:4773 lod_reset / lod_reset_op.cc).  ``y``'s data is read as
    level-0 offsets; otherwise ``target_lod`` (offsets) is required."""
    helper = LayerHelper("lod_reset")
    out = helper.create_variable_for_type_inference(x.dtype)
    length = helper.create_variable_for_type_inference("int64")
    inputs = {"X": [x]}
    attrs = {}
    if y is not None:
        inputs["Y"] = [y]
    elif target_lod is not None:
        attrs["target_lod"] = [int(v) for v in target_lod]
    else:
        raise ValueError("lod_reset needs y or target_lod")
    helper.append_op(type="lod_reset", inputs=inputs,
                     outputs={"Out": [out], "Length": [length]},
                     attrs=attrs)
    out._seq_len_name = length.name
    return out
