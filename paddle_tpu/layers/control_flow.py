"""Control-flow layer DSL (reference
``python/paddle/fluid/layers/control_flow.py``: StaticRNN:429, While:654,
ConditionalBlock:1203, Switch:1285, IfElse:1411, DynamicRNN:1541, plus the
tensor-array and compare plumbing).

TPU redesign (see ops/control_flow.py for the lowering):

* StaticRNN / DynamicRNN build a sub-block that lowers to ``lax.scan`` —
  fully differentiable through the registry's auto-vjp, so
  ``append_backward`` needs no recursive sub-block treatment.
* While lowers to ``lax.while_loop`` (forward/decoding only).
* IfElse is predicated: both branches run on the full batch and
  ``merge_lod_tensor`` selects rows by mask.
* Switch chains ``conditional_block`` ops (lax.cond) whose case bodies
  assign into pre-created outer vars — the piecewise-LR pattern.
* Tensor arrays are fixed-capacity ([capacity, ...]) device arrays.
"""

import contextlib

from ..framework import Variable
from ..layer_helper import LayerHelper
from .. import unique_name

__all__ = [
    "StaticRNN", "DynamicRNN", "While", "IfElse", "Switch",
    "ConditionalBlock", "array_write", "array_read", "array_length",
    "create_array", "beam_search", "beam_search_decode",
    "Print", "is_empty",
    "lod_rank_table", "max_sequence_len", "reorder_lod_tensor_by_rank",
    "lod_tensor_to_array", "array_to_lod_tensor",
]


def _current_block(helper):
    return helper.main_program.current_block()


def _classify_externals(sub_block, bound_names):
    """Find names read by ``sub_block``'s ops that are defined outside it.

    Returns (float_names, other_names): separated so integer externals
    (e.g. id tensors) never poison the differentiable Params slot of the
    enclosing sub-block op.
    """
    from ..core import dtype_is_floating

    bound = set(bound_names)
    floats, others, seen = [], [], set()
    for op in sub_block.ops:
        for n in op.input_arg_names:
            if not n or n in bound or n in seen or n in sub_block.vars:
                continue
            seen.add(n)
            v = sub_block._find_var_recursive(n)
            if v is None:
                continue
            if v.dtype is not None and dtype_is_floating(v.dtype):
                floats.append(n)
            else:
                others.append(n)
    return floats, others


def _written_outer_vars(sub_block):
    """Names written by sub-block ops that live in an ancestor block."""
    out = []
    for op in sub_block.ops:
        for n in op.output_arg_names:
            if n and n not in sub_block.vars and n not in out:
                if sub_block.parent_block is not None and \
                        sub_block.parent_block._find_var_recursive(n):
                    out.append(n)
    return out


# ---------------------------------------------------------------------------
# StaticRNN (reference control_flow.py:429) — fixed-length, time-major
# ---------------------------------------------------------------------------

class StaticRNN:
    """Time-major recurrence over ``[T, B, ...]`` inputs, lax.scan-lowered.

    ::

        rnn = StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)           # x: [T, B, D]
            h_pre = rnn.memory(init=h0)       # or shape=/batch_ref=
            h = layers.fc(concat([x_t, h_pre]), size=H, act='tanh')
            rnn.update_memory(h_pre, h)
            rnn.step_output(h)
        out = rnn()                            # [T, B, H]
    """

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self.sub_block = None
        self.inputs = []           # (outer var, in-block step var)
        self.memories = {}         # pre var name -> (init var, pre var)
        self.mem_updates = {}      # pre var name -> updated in-block var
        self.outputs = []          # in-block vars to stack
        self.time_major = True

    @contextlib.contextmanager
    def step(self):
        if self.status != StaticRNN.BEFORE_RNN_BLOCK:
            raise RuntimeError("step() may only be entered once")
        program = self.helper.main_program
        self.parent_block = program.current_block()
        self.sub_block = program._create_block()
        self.status = StaticRNN.IN_RNN_BLOCK
        try:
            yield
        finally:
            program._rollback()
        self.status = StaticRNN.AFTER_RNN_BLOCK
        self._complete_op()

    def _assert_in_rnn_block(self, method):
        if self.status != StaticRNN.IN_RNN_BLOCK:
            raise RuntimeError("%s() may only be called inside rnn.step()"
                               % method)

    def step_input(self, x):
        self._assert_in_rnn_block("step_input")
        if not isinstance(x, Variable):
            raise TypeError("step_input needs a Variable")
        step_var = self.sub_block.create_var(
            name=unique_name.generate(x.name + "@step"),
            shape=tuple(x.shape[1:]), dtype=x.dtype)
        self.inputs.append((x, step_var))
        return step_var

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1,
               dtype="float32"):
        self._assert_in_rnn_block("memory")
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError(
                    "memory() needs init=, or shape= AND batch_ref=")
            # the init op is emitted in the parent block, so a step var
            # reference must be remapped to its outer source sequence
            for outer, step_var in self.inputs:
                if batch_ref is step_var or batch_ref.name == step_var.name:
                    batch_ref = outer
                    ref_batch_dim_idx = 1 if self.time_major else 0
                    break
            from . import tensor as tensor_layers
            parent = self.parent_block
            program = self.helper.main_program
            # temporarily emit the zero-init in the parent block
            saved = program.current_block_idx
            program.current_block_idx = parent.idx
            try:
                init = tensor_layers.fill_constant_batch_size_like(
                    input=batch_ref, shape=[-1] + list(shape),
                    dtype=dtype, value=init_value,
                    input_dim_idx=ref_batch_dim_idx,
                    output_dim_idx=init_batch_dim_idx)
            finally:
                program.current_block_idx = saved
        if getattr(init, "op", None) is not None and \
                init.op in self.sub_block.ops:
            raise ValueError(
                "memory init var %r is produced inside the step block; "
                "create it before entering step()/block()" % init.name)
        pre = self.sub_block.create_var(
            name=unique_name.generate("%s@mem" % init.name),
            shape=tuple(init.shape), dtype=init.dtype)
        self.memories[pre.name] = (init, pre)
        return pre

    def update_memory(self, mem, var):
        self._assert_in_rnn_block("update_memory")
        if mem.name not in self.memories:
            raise ValueError("%r is not a memory of this RNN" % mem.name)
        self.mem_updates[mem.name] = var

    def step_output(self, o):
        self._assert_in_rnn_block("step_output")
        self.outputs.append(o)

    output = step_output

    def _complete_op(self):
        if not self.inputs:
            raise ValueError("StaticRNN needs at least one step_input")
        for pre_name in self.memories:
            if pre_name not in self.mem_updates:
                raise ValueError(
                    "memory %r has no update_memory()" % pre_name)
        helper = self.helper
        parent = self.parent_block
        program = helper.main_program
        saved = program.current_block_idx
        program.current_block_idx = parent.idx
        try:
            self._append_recurrent(parent)
        finally:
            program.current_block_idx = saved

    def _append_recurrent(self, parent):
        from ..core import dtype_is_floating

        helper = self.helper
        pre_names = list(self.memories.keys())
        init_vars = [self.memories[n][0] for n in pre_names]
        post_names = [self.mem_updates[n].name for n in pre_names]
        out_names = [o.name for o in self.outputs]

        # float/int step inputs ride separate op slots (see recurrent op)
        float_in, int_in = [], []
        for outer, sv in self.inputs:
            dt = sv.dtype
            if dt is not None and dtype_is_floating(dt):
                float_in.append((outer, sv))
            else:
                int_in.append((outer, sv))
        step_in_names = [sv.name for _, sv in float_in]
        int_step_in_names = [sv.name for _, sv in int_in]

        bound = set(step_in_names) | set(int_step_in_names) | set(pre_names)
        params, consts = _classify_externals(self.sub_block, bound)

        self._out_vars = [
            parent.create_var(
                name=unique_name.generate("%s@out" % o.name))
            for o in self.outputs
        ]
        final_vars = [
            parent.create_var(
                name=unique_name.generate("%s@final" % n))
            for n in post_names
        ]
        parent.append_op(
            type="recurrent",
            inputs={
                "Inputs": [x.name for x, _ in float_in],
                "IntInputs": [x.name for x, _ in int_in],
                "InitStates": [v.name for v in init_vars],
                "Params": params,
                "Consts": consts,
            },
            outputs={
                "Outputs": [v.name for v in self._out_vars],
                "FinalStates": [v.name for v in final_vars],
            },
            attrs={
                "sub_block": self.sub_block.idx,
                "time_major": self.time_major,
                "is_reverse": False,
                "step_input_names": step_in_names,
                "int_step_input_names": int_step_in_names,
                "pre_state_names": pre_names,
                "state_names": post_names,
                "output_names": out_names,
                "param_names": params,
                "const_names": consts,
            })
        self._final_vars = final_vars

    def __call__(self):
        if self.status != StaticRNN.AFTER_RNN_BLOCK:
            raise RuntimeError("RNN output requested before step() closed")
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return tuple(self._out_vars)


# ---------------------------------------------------------------------------
# DynamicRNN (reference control_flow.py:1541) — batch-major padded
# sequences masked by the @LEN companion
# ---------------------------------------------------------------------------

class DynamicRNN(StaticRNN):
    """Recurrence over padded ``[B, T, ...]`` sequences.  Steps past a
    row's length leave memories unchanged and emit zeros (the padded-batch
    redesign of the reference's shrink-memory machinery)."""

    def __init__(self, name=None):
        super().__init__(name=name)
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.time_major = False
        self._length_var = None

    block = StaticRNN.step          # reference API name

    def static_input(self, x):
        """Reference control_flow.py DynamicRNN.static_input: expose a
        non-stepped tensor inside the block.  The padded-batch redesign
        needs no LoD reorder — outer vars are directly visible to the
        sub-block — so this is an identity kept for API parity."""
        self._assert_in_rnn_block("static_input")
        return x

    def step_input(self, x, length=None):
        self._assert_in_rnn_block("step_input")
        if length is None:
            from .sequence import _len_of
            length = _len_of(self.helper, x, None)
        if self._length_var is None:
            self._length_var = length
        step_var = self.sub_block.create_var(
            name=unique_name.generate(x.name + "@step"),
            shape=tuple(x.shape[:1]) + tuple(x.shape[2:]), dtype=x.dtype)
        self.inputs.append((x, step_var))
        return step_var

    def memory(self, init=None, shape=None, value=0.0, dtype="float32",
               **kwargs):
        if init is None and shape is not None and self.inputs:
            kwargs.setdefault("batch_ref", self.inputs[0][0])
            kwargs.setdefault("ref_batch_dim_idx", 0)
            return super().memory(shape=shape, init_value=value,
                                  dtype=dtype, **kwargs)
        return super().memory(init=init, shape=shape, init_value=value,
                              dtype=dtype, **kwargs)

    def _append_recurrent(self, parent):
        super()._append_recurrent(parent)
        op = parent.ops[-1]
        assert op.type == "recurrent"
        if self._length_var is not None:
            op.inputs["Length"] = [self._length_var.name]
            for v in self._out_vars:
                v._seq_len_name = self._length_var.name


# ---------------------------------------------------------------------------
# While (reference control_flow.py:654)
# ---------------------------------------------------------------------------

class While:
    """``lax.while_loop`` over a sub-block.  ``cond`` is a [1] bool var;
    the block must update it (e.g. ``layers.less_than(i, n, cond=cond)``).
    Vars written inside the block that already exist outside are the loop
    carry; their shapes must be loop-invariant (use fixed-capacity arrays
    from ``create_array``/``array_write``).

    Gradients: an unbounded ``lax.while_loop`` cannot be reverse-
    differentiated by XLA.  Declaring ``max_trip_count=N`` lowers the
    loop to a bounded, predicated ``lax.scan`` (each of the N steps
    either runs the body or passes the carry through once the condition
    has gone false) — functionally identical for any execution taking
    <= N trips, and differentiable, matching the reference's WhileGrad
    capability (``while_op.cc:101``).  Without it, a backward through
    the loop raises with this explanation."""

    def __init__(self, cond, name=None, max_trip_count=None):
        self.helper = LayerHelper("while", name=name)
        if not isinstance(cond, Variable):
            raise TypeError("cond must be a Variable")
        if max_trip_count is not None and int(max_trip_count) <= 0:
            raise ValueError("max_trip_count must be positive")
        self.cond_var = cond
        self.max_trip_count = max_trip_count

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent = program.current_block()
        sub = program._create_block()
        try:
            yield
        finally:
            program._rollback()

        carried = _written_outer_vars(sub)
        if self.cond_var.name not in carried:
            raise ValueError(
                "While block must update the condition var %r (e.g. "
                "layers.less_than(..., cond=cond))" % self.cond_var.name)
        params, consts = _classify_externals(sub, set(carried))
        # snapshot the initial carry under distinct names: the op's
        # outputs alias the carried vars (the reference's in-place while
        # contract), so without snapshots a later grad op reading
        # LoopVars from the trace env would see the FINAL values — the
        # re-run loop's condition would already be false and every
        # gradient through the loop would silently be zero
        snaps = []
        for c in carried:
            snap = c + "@LOOP_IN"
            cv = parent._find_var_recursive(c)
            parent.create_var(name=snap, shape=cv.shape, dtype=cv.dtype,
                              persistable=False)
            parent.append_op(type="assign", inputs={"X": [c]},
                             outputs={"Out": [snap]}, attrs={})
            snaps.append(snap)
        parent.append_op(
            type="while",
            inputs={
                "Condition": [self.cond_var.name + "@LOOP_IN"],
                "LoopVars": snaps,
                "Params": params,
                "Consts": consts,
            },
            outputs={"Out": list(carried)},
            attrs={
                "sub_block": sub.idx,
                "carried_names": list(carried),
                "cond_name": self.cond_var.name,
                "param_names": params,
                "const_names": consts,
                "max_trip_count": int(self.max_trip_count or 0),
            })


# ---------------------------------------------------------------------------
# ConditionalBlock / Switch (reference control_flow.py:1203 / 1285)
# ---------------------------------------------------------------------------

class ConditionalBlock:
    """Run a sub-block only when every input cond is true (lax.cond).
    The block communicates by assigning into vars that already exist
    outside it; reads of outer vars are captured automatically."""

    def __init__(self, inputs, is_scalar_condition=True, name=None):
        self.helper = LayerHelper("conditional_block", name=name)
        if not is_scalar_condition:
            raise NotImplementedError(
                "per-row (non-scalar) conditions are served by IfElse "
                "(predicated row merge); ConditionalBlock lowers to "
                "lax.cond over a scalar predicate")
        for x in inputs:
            if not isinstance(x, Variable):
                raise TypeError("ConditionalBlock inputs must be Variables")
        self.inputs = inputs

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent = program.current_block()
        sub = program._create_block()
        try:
            yield
        finally:
            program._rollback()

        cond = self.inputs[0]
        if len(self.inputs) > 1:
            # all conds must hold: AND-reduce in the parent block
            from . import tensor as tensor_layers
            for extra in self.inputs[1:]:
                cond = tensor_layers.logical_and(cond, extra)

        carried = _written_outer_vars(sub)
        params, consts = _classify_externals(sub, set(carried))
        parent.append_op(
            type="conditional_block",
            inputs={
                "Cond": [cond.name],
                "LoopVars": list(carried),
                "Params": params,
                "Consts": consts,
            },
            outputs={"Out": list(carried)},
            attrs={
                "sub_block": sub.idx,
                "carried_names": list(carried),
                "param_names": params,
                "const_names": consts,
            })


class Switch:
    """``with switch.case(cond):`` chains — each case body runs iff its
    cond holds and no earlier case fired (reference Switch semantics,
    used by piecewise LR decay)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.inside = False
        self.pre_not_taken = None     # [1] bool: no previous case fired

    def __enter__(self):
        self.inside = True
        return self

    def __exit__(self, *exc):
        self.inside = False
        return False

    def _not(self, v):
        from . import tensor as tensor_layers
        return tensor_layers.logical_not(v)

    def _and(self, a, b):
        from . import tensor as tensor_layers
        return tensor_layers.logical_and(a, b)

    def case(self, condition):
        if not self.inside:
            raise RuntimeError("case() must be used inside 'with Switch()'")
        if self.pre_not_taken is None:
            fire = condition
            self.pre_not_taken = self._not(condition)
        else:
            fire = self._and(self.pre_not_taken, condition)
            self.pre_not_taken = self._and(self.pre_not_taken,
                                           self._not(condition))
        return ConditionalBlock([fire]).block()

    def default(self):
        if self.pre_not_taken is None:
            raise RuntimeError("default() requires at least one case()")
        return ConditionalBlock([self.pre_not_taken]).block()


# ---------------------------------------------------------------------------
# IfElse (reference control_flow.py:1411) — predicated
# ---------------------------------------------------------------------------

class IfElse:
    """Row-wise branch select.  Both branches compute on the full batch;
    the outputs are merged row-by-row with the [B, 1] bool cond (the
    predication redesign of split/merge_lod_tensor — identical results
    for the row-wise nets IfElse is used with, and no dynamic shapes)."""

    OUT_IF_ELSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        if not isinstance(cond, Variable):
            raise TypeError("cond must be a Variable")
        self.cond = cond
        self._true_outs = []
        self._false_outs = []
        self._cur = None
        self._in_true = False

    @contextlib.contextmanager
    def true_block(self):
        self._cur, self._in_true = self._true_outs, True
        yield
        self._cur = None

    @contextlib.contextmanager
    def false_block(self):
        self._cur, self._in_true = self._false_outs, False
        yield
        self._cur = None

    def input(self, x):
        if self._cur is None:
            raise RuntimeError("input() only valid inside a branch block")
        # predication: the branch sees the full batch
        helper = LayerHelper("ifelse_input")
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(
            type="split_lod_tensor",
            inputs={"X": [x], "Mask": [self.cond.name]},
            outputs={"OutTrue" if self._in_true else "OutFalse": [out],
                     "OutFalse" if self._in_true else "OutTrue":
                         [helper.create_variable_for_type_inference(
                             dtype=x.dtype).name]},
        )
        return out

    def output(self, *outs):
        if self._cur is None:
            raise RuntimeError("output() only valid inside a branch block")
        self._cur.extend(outs)

    def __call__(self):
        if len(self._true_outs) != len(self._false_outs):
            raise ValueError(
                "true_block produced %d outputs, false_block %d"
                % (len(self._true_outs), len(self._false_outs)))
        if not self._true_outs:
            raise ValueError("IfElse produced no outputs")
        helper = LayerHelper("ifelse_merge")
        merged = []
        for t, f in zip(self._true_outs, self._false_outs):
            out = helper.create_variable_for_type_inference(dtype=t.dtype)
            helper.append_op(
                type="merge_lod_tensor",
                inputs={"Mask": [self.cond.name], "InTrue": [t.name],
                        "InFalse": [f.name]},
                outputs={"Out": [out]},
            )
            merged.append(out)
        return merged if len(merged) > 1 else merged[0]


# ---------------------------------------------------------------------------
# tensor arrays (fixed capacity)
# ---------------------------------------------------------------------------

def create_array(dtype, capacity, element_shape):
    """Preallocate a [capacity, *element_shape] zero array (the reference's
    LoDTensorArray grows dynamically; XLA needs the capacity up front)."""
    from . import tensor as tensor_layers
    return tensor_layers.fill_constant(
        shape=[capacity] + list(element_shape), dtype=dtype, value=0)


def array_write(x, i, array=None, capacity=None):
    """array[i] = x.  Returns the updated array (functional update; inside
    a While block write back to the same var for the loop carry)."""
    helper = LayerHelper("array_write")
    inputs = {"X": [x], "I": [i]}
    attrs = {}
    if array is not None:
        inputs["Array"] = [array]
        out = array           # in-place style: same var carries the value
    else:
        if capacity is None:
            raise ValueError("array_write needs array= or capacity=")
        attrs["capacity"] = int(capacity)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="array_write", inputs=inputs,
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(dtype=array.dtype)
    helper.append_op(
        type="array_read", inputs={"Array": [array], "I": [i]},
        outputs={"Out": [out]})
    return out


def array_length(array):
    """Capacity of the array as a [1] int64 tensor (static on TPU)."""
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="lod_array_length", inputs={"X": [array]},
        outputs={"Out": [out]})
    return out


# ---------------------------------------------------------------------------
# beam search layers (reference layers/nn.py beam_search)
# ---------------------------------------------------------------------------

def beam_search(pre_ids, pre_scores, scores, beam_size, end_id, name=None):
    """One beam-search step over ``[B, K]`` beams.

    ``scores`` are the step's log-probs ``[B, K, V]``; returns
    (selected_ids [B,K], selected_scores [B,K], parent_idx [B,K]).
    Initialize ``pre_scores`` to ``[0, -inf, ...]`` per row so the first
    expansion is seeded from beam 0 only.
    """
    helper = LayerHelper("beam_search", name=name)
    ids = helper.create_variable_for_type_inference(dtype="int64")
    sc = helper.create_variable_for_type_inference(dtype=pre_scores.dtype)
    parent = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="beam_search",
        inputs={"PreIds": [pre_ids], "PreScores": [pre_scores],
                "Scores": [scores]},
        outputs={"SelectedIds": [ids], "SelectedScores": [sc],
                 "ParentIdx": [parent]},
        attrs={"beam_size": beam_size, "end_id": end_id})
    return ids, sc, parent


def beam_search_decode(ids, parents, scores, beam_size, end_id, name=None):
    """Backtrack stacked per-step ids/parents ``[T, B, K]`` into full
    sequences ``[B, K, T]`` plus final scores ``[B, K]``."""
    helper = LayerHelper("beam_search_decode", name=name)
    sent = helper.create_variable_for_type_inference(dtype="int64")
    sc = helper.create_variable_for_type_inference(dtype=scores.dtype)
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids], "Parents": [parents], "Scores": [scores]},
        outputs={"SentenceIds": [sent], "SentenceScores": [sc]},
        attrs={"beam_size": beam_size, "end_id": end_id})
    return sent, sc


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """In-graph tensor printing (reference control_flow.py:146 Print /
    print_op.cc), lowered to ``jax.debug.print`` — fires every execution
    (``first_n``/``summarize`` are accepted for API parity; XLA has no
    cross-step counter for first_n without threading state)."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="print", inputs={"In": [input]}, outputs={"Out": [out]},
        attrs={
            "first_n": first_n,
            "message": message or "",
            "summarize": summarize,
            "print_tensor_name": print_tensor_name,
            "print_tensor_type": print_tensor_type,
            "print_tensor_shape": print_tensor_shape,
            "print_tensor_lod": print_tensor_lod,
            "print_phase": print_phase.upper(),
            "__var_name__": input.name,
        })
    return out


def is_empty(x, cond=None):
    """Whether ``x`` has zero elements (reference control_flow.py:1936 /
    is_empty_op.cc).  Shapes are static under XLA, so the result is a
    compile-time constant materialized as a [1] bool tensor."""
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="is_empty", inputs={"X": [x]},
                     outputs={"Out": [cond]})
    return cond


def lod_rank_table(x, level=0):
    """Build a rank table over ``x``'s sequences: (index, length) rows
    sorted by length descending, stable (reference
    ``lod_rank_table_op.cc:1`` / control_flow.py lod_rank_table).

    On the padded design the table is a plain [B, 2] int64 tensor read
    from the @LEN companion; only ``level=0`` exists because padded
    batches carry one nesting level (SURVEY §5 long-context ruling —
    deeper nesting is packed host-side)."""
    if level != 0:
        raise NotImplementedError(
            "lod_rank_table: only level=0 exists on the padded+@LEN "
            "design; nested LoD levels are flattened host-side")
    from .sequence import sequence_length
    helper = LayerHelper("lod_rank_table", input=x)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="lod_rank_table",
        inputs={"Length": [sequence_length(x)]},
        outputs={"Out": [out]})
    return out


def max_sequence_len(rank_table):
    """Longest sequence length recorded in a rank table (reference
    ``max_sequence_len_op.cc:1``)."""
    helper = LayerHelper("max_sequence_len", input=rank_table)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="max_sequence_len",
        inputs={"RankTable": [rank_table]},
        outputs={"Out": [out]})
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    """Reorder a batch by a rank table's index column — longest
    sequences first (reference ``reorder_lod_tensor_by_rank_op.cc:1``).
    The reordered output carries a reordered @LEN companion, so every
    downstream sequence op masks correctly."""
    helper = LayerHelper("reorder_lod_tensor_by_rank", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    out_len = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="reorder_lod_tensor_by_rank",
        inputs={"X": [x], "RankTable": [rank_table]},
        outputs={"Out": [out], "OutLength": [out_len]})
    out._seq_len_name = out_len.name
    return out


def lod_tensor_to_array(x, table):
    """[B, T, ...] batch -> time-major rank-ordered step batches
    (reference ``lod_tensor_to_array_op.cc:1``; see the op doc for the
    static-shape redesign of the reference's shrinking step batches)."""
    helper = LayerHelper("lod_tensor_to_array", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    out_len = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="lod_tensor_to_array",
        inputs={"X": [x], "RankTable": [table]},
        outputs={"Out": [out], "OutLength": [out_len]})
    out._seq_len_name = out_len.name
    return out


def array_to_lod_tensor(x, table):
    """Inverse of lod_tensor_to_array (reference
    ``array_to_lod_tensor_op.cc:1``)."""
    helper = LayerHelper("array_to_lod_tensor", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    out_len = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="array_to_lod_tensor",
        inputs={"X": [x], "RankTable": [table]},
        outputs={"Out": [out], "OutLength": [out_len]})
    out._seq_len_name = out_len.name
    return out
