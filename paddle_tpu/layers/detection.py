"""Detection layer DSL (reference ``python/paddle/fluid/layers/
detection.py``: prior_box, anchor_generator, box_coder, iou_similarity,
bipartite_match, target_assign, multiclass NMS via detection_output,
roi_pool, polygon_box_transform)."""

from ..layer_helper import LayerHelper

__all__ = [
    "multi_box_head",
    "prior_box",
    "anchor_generator",
    "box_coder",
    "iou_similarity",
    "bipartite_match",
    "target_assign",
    "multiclass_nms",
    "detection_output",
    "roi_pool",
    "polygon_box_transform",
    "mine_hard_examples",
    "ssd_loss",
    "generate_proposals",
    "rpn_target_assign",
    "generate_proposal_labels",
    "roi_perspective_transform",
    "detection_map",
]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=None, flip=False, clip=False, steps=None,
              offset=0.5, min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes over a feature map (reference detection.py
    prior_box / prior_box_op.h).  Returns (boxes, variances), each
    [H, W, num_priors, 4]."""
    helper = LayerHelper("prior_box", input=input, name=name)
    boxes = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    steps = steps or [0.0, 0.0]
    helper.append_op(
        type="prior_box", inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={"min_sizes": list(min_sizes),
               "max_sizes": list(max_sizes or []),
               "aspect_ratios": list(aspect_ratios or [1.0]),
               "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
               "flip": bool(flip), "clip": bool(clip),
               "step_w": float(steps[0]), "step_h": float(steps[1]),
               "offset": float(offset),
               "min_max_aspect_ratios_order":
                   bool(min_max_aspect_ratios_order)})
    for v in (boxes, variances):
        v.stop_gradient = True
    return boxes, variances


def anchor_generator(input, anchor_sizes, aspect_ratios, variance=None,
                     stride=None, offset=0.5, name=None):
    """RPN anchors (reference anchor_generator_op.h).  Returns
    (anchors, variances) [H, W, num_anchors, 4]."""
    helper = LayerHelper("anchor_generator", input=input, name=name)
    anchors = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="anchor_generator", inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [variances]},
        attrs={"anchor_sizes": [float(s) for s in anchor_sizes],
               "aspect_ratios": [float(a) for a in aspect_ratios],
               "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
               "stride": [float(s) for s in (stride or [16.0, 16.0])],
               "offset": float(offset)})
    for v in (anchors, variances):
        v.stop_gradient = True
    return anchors, variances


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    helper = LayerHelper("box_coder", input=target_box, name=name)
    out = helper.create_variable_for_type_inference("float32")
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="box_coder", inputs=inputs, outputs={"OutputBox": [out]},
        attrs={"code_type": code_type,
               "box_normalized": bool(box_normalized)})
    return out


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """Returns (match_indices [B, P] int32, match_dist [B, P]).
    ``match_type='per_prediction'`` additionally matches unmatched
    columns whose best dist >= ``dist_threshold`` (default 0.5)."""
    helper = LayerHelper("bipartite_match", input=dist_matrix, name=name)
    match = helper.create_variable_for_type_inference("int32")
    mdist = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="bipartite_match", inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [match],
                 "ColToRowMatchDist": [mdist]},
        attrs={"match_type": match_type or "bipartite",
               "dist_threshold": float(dist_threshold or 0.5)})
    match.stop_gradient = True
    mdist.stop_gradient = True
    return match, mdist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    """Returns (out [B, P, K], out_weight [B, P, 1])."""
    helper = LayerHelper("target_assign", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_weight = helper.create_variable_for_type_inference("float32")
    inputs = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices]
    helper.append_op(
        type="target_assign", inputs=inputs,
        outputs={"Out": [out], "OutWeight": [out_weight]},
        attrs={"mismatch_value": int(mismatch_value)})
    out.stop_gradient = True
    out_weight.stop_gradient = True
    return out, out_weight


def multiclass_nms(bboxes, scores, score_threshold=0.0,
                   nms_top_k=-1, nms_threshold=0.3, keep_top_k=-1,
                   normalized=True, background_label=0, name=None):
    """Per-class NMS; returns detections [B, keep_top_k, 6]
    ((label, score, x1, y1, x2, y2), -1-labeled rows are padding) with
    a per-image count companion (sequence-length convention)."""
    helper = LayerHelper("multiclass_nms", input=bboxes, name=name)
    out = helper.create_variable_for_type_inference("float32")
    out_len = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out], "OutLength": [out_len]},
        attrs={"score_threshold": float(score_threshold),
               "nms_top_k": int(nms_top_k),
               "nms_threshold": float(nms_threshold),
               "keep_top_k": int(keep_top_k),
               "normalized": bool(normalized),
               "background_label": int(background_label)})
    out.stop_gradient = True
    out._seq_len_name = out_len.name
    return out


detection_output = multiclass_nms  # reference alias: decode+nms tail


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_batch=None, name=None):
    helper = LayerHelper("roi_pool", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch is not None:
        inputs["RoisBatch"] = [rois_batch]
    helper.append_op(
        type="roi_pool", inputs=inputs, outputs={"Out": [out]},
        attrs={"pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width),
               "spatial_scale": float(spatial_scale)})
    return out


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="polygon_box_transform",
                     inputs={"X": [input]}, outputs={"Out": [out]})
    return out


def mine_hard_examples(cls_loss, match_indices, match_dist, loc_loss=None,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       mining_type="max_negative", sample_size=None,
                       name=None):
    """Hard-negative mining (mine_hard_examples_op.cc, max_negative
    mode).  Returns (neg_indices [B, P] -1-padded, updated_match)."""
    helper = LayerHelper("mine_hard_examples", input=cls_loss, name=name)
    neg = helper.create_variable_for_type_inference("int32")
    neg_count = helper.create_variable_for_type_inference("int32")
    updated = helper.create_variable_for_type_inference("int32")
    inputs = {"ClsLoss": [cls_loss], "MatchIndices": [match_indices],
              "MatchDist": [match_dist]}
    if loc_loss is not None:
        inputs["LocLoss"] = [loc_loss]
    helper.append_op(
        type="mine_hard_examples", inputs=inputs,
        outputs={"NegIndices": [neg], "NegCount": [neg_count],
                 "UpdatedMatchIndices": [updated]},
        attrs={"neg_pos_ratio": float(neg_pos_ratio),
               "neg_dist_threshold": float(neg_dist_threshold),
               "mining_type": mining_type,
               "sample_size": int(sample_size or -1)})
    for v in (neg, neg_count, updated):
        v.stop_gradient = True
    # the count rides as neg's length companion (padded-array convention)
    neg._seq_len_name = neg_count.name
    return neg, updated


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0,
             overlap_threshold=0.5, neg_pos_ratio=3.0, neg_overlap=0.5,
             loc_loss_weight=1.0, conf_loss_weight=1.0,
             match_type="per_prediction", mining_type="max_negative",
             normalize=True, sample_size=None):
    """SSD multibox loss (reference detection.py:662 ssd_loss): match
    priors to ground truth, mine hard negatives, and combine smooth-L1
    localization loss with softmax confidence loss.

    Shapes (padded-batch convention): location [B, P, 4], confidence
    [B, P, C], gt_box [B, G, 4], gt_label [B, G, 1] (pad gt rows with
    boxes of zero area), prior_box [P, 4].  Returns the per-prior
    weighted loss [B, P, 1].
    """
    from .. import layers as L  # composite of existing layers/ops

    # 1. match: iou [B, G, P] -> per-prior matched gt row
    iou = iou_similarity(gt_box, prior_box)
    matched, match_dist = bipartite_match(iou, match_type,
                                          overlap_threshold)

    # 2. confidence targets for mining: background where unmatched
    tgt_label, _ = target_assign(gt_label, matched,
                                 mismatch_value=background_label)
    tgt_label = L.cast(tgt_label, "int64")
    mining_conf_loss = L.softmax_with_cross_entropy(confidence, tgt_label)

    # 3. hard-negative mining
    neg_indices, updated = mine_hard_examples(
        L.reshape(mining_conf_loss, shape=[0, -1]), matched, match_dist,
        neg_pos_ratio=neg_pos_ratio, neg_dist_threshold=neg_overlap,
        mining_type=mining_type, sample_size=sample_size)

    # 4. confidence loss weighted over positives + mined negatives
    # (reuses the mining pass's cross-entropy — same op, same inputs)
    _, conf_wt = target_assign(gt_label, matched,
                               negative_indices=neg_indices,
                               mismatch_value=background_label)
    conf_loss = L.elementwise_mul(mining_conf_loss, conf_wt)

    # 5. localization targets: encode gt against priors, gather matched
    gt_flat = L.reshape(gt_box, shape=[-1, 4])
    enc = box_coder(prior_box, prior_box_var, gt_flat,
                    "encode_center_size")           # [B*G, P, 4]
    enc = L.reshape(
        enc, shape=[-1, gt_box.shape[1], prior_box.shape[0], 4])
    loc_target, loc_wt = target_assign(enc, matched, mismatch_value=0)
    # per-prior smooth-L1 via clip identity: with m = clip(|d|, 0, 1),
    # 0.5*m^2 + (|d| - m) equals 0.5 d^2 for |d|<1 and |d|-0.5 beyond
    ad = L.abs(L.elementwise_sub(location, loc_target))
    m = L.clip(ad, min=0.0, max=1.0)
    sl1 = L.elementwise_add(
        L.scale(L.elementwise_mul(m, m), scale=0.5),
        L.elementwise_sub(ad, m))
    loc_loss = L.reduce_sum(sl1, dim=-1, keep_dim=True)
    loc_loss = L.elementwise_mul(loc_loss, loc_wt)

    loss = L.elementwise_add(L.scale(loc_loss, scale=loc_loss_weight),
                             L.scale(conf_loss, scale=conf_loss_weight))
    if normalize:
        num_matched = L.reduce_sum(loc_wt) + 1e-6
        loss = L.elementwise_div(loss, num_matched)
    return loss


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """RPN proposal generation (reference generate_proposals_op.cc):
    decode top-scoring anchor deltas, clip to the image, drop tiny
    boxes, NMS.  Returns (rois [B, post_n, 4], probs [B, post_n, 1])
    with the per-image count as rois' length companion.  ``eta`` is
    accepted for API parity; only eta=1.0 (fixed-threshold NMS) is
    implemented and other values raise at run time."""
    helper = LayerHelper("generate_proposals", input=scores, name=name)
    rois = helper.create_variable_for_type_inference("float32")
    probs = helper.create_variable_for_type_inference("float32")
    count = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="generate_proposals",
        inputs={"Scores": [scores], "BboxDeltas": [bbox_deltas],
                "ImInfo": [im_info], "Anchors": [anchors],
                "Variances": [variances]},
        outputs={"RpnRois": [rois], "RpnRoiProbs": [probs],
                 "RpnRoisLength": [count]},
        attrs={"pre_nms_topN": int(pre_nms_top_n),
               "post_nms_topN": int(post_nms_top_n),
               "nms_thresh": float(nms_thresh),
               "min_size": float(min_size), "eta": float(eta)})
    for v in (rois, probs, count):
        v.stop_gradient = True
    rois._seq_len_name = count.name
    return rois, probs


def rpn_target_assign(anchor, gt_boxes, rpn_batch_size_per_im=256,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, gt_length=None,
                      name=None):
    """RPN training targets (reference rpn_target_assign_op.cc),
    static-shape form: per-anchor labels [B, A] (1 fg / 0 bg / -1
    ignore), encoded regression targets [B, A, 4], and fg weights
    [B, A, 1] — mask-based instead of the reference's index lists
    (deterministic first-k subsampling replaces reservoir sampling)."""
    helper = LayerHelper("rpn_target_assign", input=anchor, name=name)
    labels = helper.create_variable_for_type_inference("int32")
    tgt = helper.create_variable_for_type_inference("float32")
    weight = helper.create_variable_for_type_inference("float32")
    inputs = {"Anchor": [anchor], "GtBoxes": [gt_boxes]}
    if gt_length is not None:
        inputs["GtLength"] = [gt_length]
    helper.append_op(
        type="rpn_target_assign", inputs=inputs,
        outputs={"ScoreLabels": [labels], "TargetBBox": [tgt],
                 "BBoxWeight": [weight]},
        attrs={"rpn_batch_size_per_im": int(rpn_batch_size_per_im),
               "rpn_fg_fraction": float(rpn_fg_fraction),
               "rpn_positive_overlap": float(rpn_positive_overlap),
               "rpn_negative_overlap": float(rpn_negative_overlap)})
    for v in (labels, tgt, weight):
        v.stop_gradient = True
    return labels, tgt, weight


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             rpn_rois_length=None, gt_length=None):
    """Sample RoIs + classification/regression targets for Faster-RCNN
    training (reference detection.py:1401 /
    generate_proposal_labels_op.cc).  Padded-batch convention: inputs are
    [B, ...]; outputs carry a fixed ``batch_size_per_im`` rows per image
    with RoisNum as the valid-count companion.

    Returns (rois, labels_int32, bbox_targets, bbox_inside_weights,
    bbox_outside_weights)."""
    helper = LayerHelper("generate_proposal_labels", input=rpn_rois)
    rois = helper.create_variable_for_type_inference(rpn_rois.dtype)
    labels = helper.create_variable_for_type_inference("int32")
    targets = helper.create_variable_for_type_inference(rpn_rois.dtype)
    inside = helper.create_variable_for_type_inference(rpn_rois.dtype)
    outside = helper.create_variable_for_type_inference(rpn_rois.dtype)
    num = helper.create_variable_for_type_inference("int32")
    inputs = {"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
              "IsCrowd": [is_crowd], "GtBoxes": [gt_boxes],
              "ImInfo": [im_info]}
    if rpn_rois_length is not None:
        inputs["RpnRoisLength"] = [rpn_rois_length]
    elif getattr(rpn_rois, "_seq_len_name", None):
        inputs["RpnRoisLength"] = [rpn_rois._seq_len_name]
    if gt_length is not None:
        inputs["GtLength"] = [gt_length]
    helper.append_op(
        type="generate_proposal_labels", inputs=inputs,
        outputs={"Rois": [rois], "LabelsInt32": [labels],
                 "BboxTargets": [targets], "BboxInsideWeights": [inside],
                 "BboxOutsideWeights": [outside], "RoisNum": [num]},
        attrs={"batch_size_per_im": batch_size_per_im,
               "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
               "bg_thresh_hi": bg_thresh_hi, "bg_thresh_lo": bg_thresh_lo,
               "bbox_reg_weights": list(bbox_reg_weights),
               "class_nums": class_nums, "use_random": use_random})
    for v in (rois, labels, targets, inside, outside, num):
        v.stop_gradient = True
    rois._seq_len_name = num.name
    return rois, labels, targets, inside, outside


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              rois_image_id=None):
    """Warp quadrilateral ROIs to rectangles (reference detection.py:1353
    / roi_perspective_transform_op.cc).  ``rois`` is [R, 8] corner
    coords; ``rois_image_id`` maps each ROI to its batch image (the LoD
    replacement; defaults to image 0)."""
    helper = LayerHelper("roi_perspective_transform", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_image_id is not None:
        inputs["RoisImageId"] = [rois_image_id]
    helper.append_op(
        type="roi_perspective_transform", inputs=inputs,
        outputs={"Out": [out]},
        attrs={"transformed_height": transformed_height,
               "transformed_width": transformed_width,
               "spatial_scale": spatial_scale})
    return out


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version="integral", detect_res_length=None,
                  gt_length=None):
    """In-graph mean-average-precision (reference detection.py:399 /
    detection_map_op.h) over one mini-batch.  ``detect_res`` [B, D, 6]
    (label, score, x1..y2) with its count companion, ``label`` [B, G, 5|6]
    gt rows.  ``input_states``/``out_states`` (the reference's streaming
    accumulation, dynamic-length LoD state) are not supported in-graph —
    use ``metrics.DetectionMAP`` host-side for multi-batch accumulation.

    Returns the [1] mAP tensor."""
    if input_states is not None or out_states is not None or \
            has_state is not None:
        raise ValueError(
            "detection_map: in-graph streaming state is unsupported "
            "(variable-length state; see metrics.DetectionMAP)")
    helper = LayerHelper("detection_map", input=detect_res)
    m = helper.create_variable_for_type_inference("float32")
    pos = helper.create_variable_for_type_inference("int32")
    inputs = {"DetectRes": [detect_res], "Label": [label]}
    if detect_res_length is not None:
        inputs["DetectResLength"] = [detect_res_length]
    elif getattr(detect_res, "_seq_len_name", None):
        inputs["DetectResLength"] = [detect_res._seq_len_name]
    if gt_length is not None:
        inputs["GtLength"] = [gt_length]
    helper.append_op(
        type="detection_map", inputs=inputs,
        outputs={"MAP": [m], "AccumPosCount": [pos]},
        attrs={"class_num": class_num,
               "background_label": background_label,
               "overlap_threshold": overlap_threshold,
               "evaluate_difficult": evaluate_difficult,
               "ap_type": ap_version})
    m.stop_gradient = True
    return m


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=None, flip=True, clip=False,
                   kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD prediction head (reference detection.py:1015 multi_box_head):
    per feature map, prior boxes plus location/confidence convolutions;
    returns (mbox_loc [N, P, 4], mbox_conf [N, P, C],
    boxes [P, 4], variances [P, 4]) concatenated over all maps."""
    import math

    from .cnn import conv2d
    from .tensor import concat, reshape, transpose

    num_layer = len(inputs)
    if num_layer <= 2:
        assert min_sizes is not None and max_sizes is not None, (
            "min_sizes/max_sizes must be given for <=2 feature maps")
        assert len(min_sizes) == num_layer and len(max_sizes) == num_layer
    elif min_sizes is None and max_sizes is None:
        # the SSD paper's scale schedule from min_ratio..max_ratio
        min_sizes, max_sizes = [], []
        step = int(math.floor((max_ratio - min_ratio) / (num_layer - 2)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes
        max_sizes = [base_size * 0.20] + max_sizes

    def _per_layer(seq, what):
        if seq and len(seq) != num_layer:
            raise ValueError(
                "%s must have one entry per input (%d vs %d)"
                % (what, len(seq), num_layer))
    _per_layer(aspect_ratios, "aspect_ratios")
    _per_layer(step_h, "step_h")
    _per_layer(step_w, "step_w")
    _per_layer(steps, "steps")
    if steps:
        step_w = step_h = steps

    mbox_locs, mbox_confs, box_results, var_results = [], [], [], []
    for i, feat in enumerate(inputs):
        min_size = min_sizes[i]
        max_size = max_sizes[i]
        if not isinstance(min_size, (list, tuple)):
            min_size = [min_size]
        if not isinstance(max_size, (list, tuple)):
            max_size = [max_size]
        aspect_ratio = []
        if aspect_ratios is not None:
            aspect_ratio = aspect_ratios[i]
            if not isinstance(aspect_ratio, (list, tuple)):
                aspect_ratio = [aspect_ratio]
        step = [step_w[i] if step_w else 0.0,
                step_h[i] if step_h else 0.0]

        box, var = prior_box(
            feat, image, min_size, max_size, aspect_ratio, variance,
            flip, clip, step, offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        box_results.append(box)
        var_results.append(var)
        num_boxes = box.shape[2]

        loc = conv2d(feat, num_filters=num_boxes * 4,
                     filter_size=kernel_size, padding=pad, stride=stride)
        loc = transpose(loc, perm=[0, 2, 3, 1])
        mbox_locs.append(reshape(loc, shape=[0, -1, 4]))

        conf = conv2d(feat, num_filters=num_boxes * num_classes,
                      filter_size=kernel_size, padding=pad, stride=stride)
        conf = transpose(conf, perm=[0, 2, 3, 1])
        mbox_confs.append(reshape(conf, shape=[0, -1, num_classes]))

    if num_layer == 1:
        box, var = box_results[0], var_results[0]
        mbox_locs_concat, mbox_confs_concat = mbox_locs[0], mbox_confs[0]
    else:
        box = concat([reshape(b, shape=[-1, 4]) for b in box_results])
        var = concat([reshape(v, shape=[-1, 4]) for v in var_results])
        mbox_locs_concat = concat(mbox_locs, axis=1)
        mbox_confs_concat = concat(mbox_confs, axis=1)
    box.stop_gradient = True
    var.stop_gradient = True
    return mbox_locs_concat, mbox_confs_concat, box, var
