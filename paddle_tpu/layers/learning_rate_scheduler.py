"""In-graph learning-rate schedules (reference
``python/paddle/fluid/layers/learning_rate_scheduler.py`` 345 LoC:
noam/exponential/natural_exp/inverse_time/polynomial/piecewise decay —
each emits ops into the main program so the LR updates inside the same
jitted training step).

A global step counter var increments once per step (the reference's
``_decay_step_counter``); every schedule is a pure function of it built
from registered ops, so it fuses into the step's HLO.
"""

import math

from ..layer_helper import LayerHelper

__all__ = [
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "noam_decay",
    "append_LARS",
]


def _decay_step_counter(begin=0):
    # one counter per `begin` value: schedules with different origins
    # (e.g. noam starts at 1) must not share a var or they shift each
    # other.  Delegates to the public counter builder (nn.py).
    from .nn import autoincreased_step_counter
    counter_name = "@LR_DECAY_COUNTER@" if begin == 0 else \
        "@LR_DECAY_COUNTER@begin=%d" % begin
    return autoincreased_step_counter(counter_name, begin=begin, step=1,
                                      dtype="float32")


def _scalar(helper, value, like):
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(
        type="fill_constant", outputs={"Out": [out]},
        attrs={"shape": [1], "value": float(value), "dtype": "float32",
               "force_cpu": False})
    out.stop_gradient = True
    return out


def _binary(helper, op_type, x, y):
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    out.stop_gradient = True
    return out


def _unary(helper, op_type, x, **attrs):
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(type=op_type, inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs=attrs)
    out.stop_gradient = True
    return out


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr * decay_rate ^ (step / decay_steps)"""
    helper = LayerHelper("exponential_decay")
    step = _decay_step_counter()
    div = _unary(helper, "scale", step, scale=1.0 / decay_steps, bias=0.0,
                 bias_after_scale=True)
    if staircase:
        div = _unary(helper, "floor", div)
    # rate^x = exp(x * ln rate)
    expo = _unary(helper, "scale", div, scale=math.log(decay_rate), bias=0.0,
                  bias_after_scale=True)
    factor = _unary(helper, "exp", expo)
    return _unary(helper, "scale", factor, scale=float(learning_rate),
                  bias=0.0, bias_after_scale=True)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr * exp(-decay_rate * step / decay_steps)"""
    helper = LayerHelper("natural_exp_decay")
    step = _decay_step_counter()
    div = _unary(helper, "scale", step, scale=1.0 / decay_steps, bias=0.0,
                 bias_after_scale=True)
    if staircase:
        div = _unary(helper, "floor", div)
    expo = _unary(helper, "scale", div, scale=-float(decay_rate), bias=0.0,
                  bias_after_scale=True)
    factor = _unary(helper, "exp", expo)
    return _unary(helper, "scale", factor, scale=float(learning_rate),
                  bias=0.0, bias_after_scale=True)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    """lr / (1 + decay_rate * step / decay_steps)"""
    helper = LayerHelper("inverse_time_decay")
    step = _decay_step_counter()
    div = _unary(helper, "scale", step, scale=1.0 / decay_steps, bias=0.0,
                 bias_after_scale=True)
    if staircase:
        div = _unary(helper, "floor", div)
    denom = _unary(helper, "scale", div, scale=float(decay_rate), bias=1.0,
                   bias_after_scale=True)
    recip = _unary(helper, "reciprocal", denom)
    return _unary(helper, "scale", recip, scale=float(learning_rate),
                  bias=0.0, bias_after_scale=True)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    """(lr - end_lr) * (1 - min(step, decay_steps)/decay_steps)^power + end_lr
    (cycle=True restarts with a growing decay_steps; reference
    learning_rate_scheduler.py polynomial_decay)"""
    helper = LayerHelper("polynomial_decay")
    step = _decay_step_counter()
    if cycle:
        ratio = _unary(helper, "scale", step, scale=1.0 / decay_steps,
                       bias=0.0, bias_after_scale=True)
        ceilv = _unary(helper, "ceil", ratio)
        # ensure at least one period after step 0: max(ceil(ratio), 1)
        one = _scalar(helper, 1.0, step)
        ceilv = _binary(helper, "elementwise_max", ceilv, one)
        cur_decay = _unary(helper, "scale", ceilv, scale=float(decay_steps),
                           bias=0.0, bias_after_scale=True)
        frac = _binary(helper, "elementwise_div", step, cur_decay)
    else:
        cap = _scalar(helper, float(decay_steps), step)
        capped = _binary(helper, "elementwise_min", step, cap)
        frac = _unary(helper, "scale", capped, scale=1.0 / decay_steps,
                      bias=0.0, bias_after_scale=True)
    base = _unary(helper, "scale", frac, scale=-1.0, bias=1.0,
                  bias_after_scale=True)
    # clamp: float rounding can push 1 - step/decay_steps a hair below 0,
    # and power of a negative base is NaN
    base = _unary(helper, "clip", base, min=0.0, max=1.0)
    powed = _binary(helper, "elementwise_pow", base,
                    _scalar(helper, float(power), step))
    return _unary(helper, "scale", powed,
                  scale=float(learning_rate) - float(end_learning_rate),
                  bias=float(end_learning_rate), bias_after_scale=True)


def piecewise_decay(boundaries, values):
    """Step-function schedule (reference piecewise_decay)."""
    if len(values) != len(boundaries) + 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    helper = LayerHelper("piecewise_decay")
    step = _decay_step_counter()
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(
        type="piecewise_lr", inputs={"Step": [step]},
        outputs={"Out": [out]},
        attrs={"boundaries": [float(b) for b in boundaries],
               "values": [float(v) for v in values]})
    out.stop_gradient = True
    return out


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """d_model^-0.5 * min(step^-0.5, step * warmup^-1.5) (Transformer LR;
    reference noam_decay)."""
    helper = LayerHelper("noam_decay")
    step = _decay_step_counter(begin=1)
    a = _unary(helper, "rsqrt", step)
    b = _unary(helper, "scale", step, scale=float(warmup_steps) ** -1.5,
               bias=0.0, bias_after_scale=True)
    m = _binary(helper, "elementwise_min", a, b)
    return _unary(helper, "scale", m,
                  scale=float(learning_rate) * float(d_model) ** -0.5,
                  bias=0.0, bias_after_scale=True)


def append_LARS(params_grads, learning_rate, weight_decay):
    """Layer-wise adaptive rate scaling (reference
    learning_rate_scheduler.py:310): per-parameter
    ``lr * ||w|| / (||g|| + wd * ||w||)``, written into each parameter's
    ``optimize_attr['learning_rate']`` so the optimizer's per-param LR
    multiplier picks it up.  ``learning_rate`` may be a Variable or a
    plain float (materialized as a constant, like the reference's
    scalar operator overloads)."""
    from ..framework import Variable
    helper = LayerHelper("lars")
    if not isinstance(learning_rate, Variable):
        learning_rate = _scalar(helper, float(learning_rate), None)

    def _balanced_weight(param_norm, grad_norm):
        if weight_decay == 1.0:
            return _binary(helper, "elementwise_add", grad_norm, param_norm)
        scaled = _unary(helper, "scale", param_norm,
                        scale=float(weight_decay), bias=0.0,
                        bias_after_scale=True)
        return _binary(helper, "elementwise_add", grad_norm, scaled)

    decayed = []
    for param, grad in params_grads:
        if grad is None:
            decayed.append(None)
            continue
        param_lr = param.optimize_attr.get("learning_rate", 1.0)
        p_norm = _unary(helper, "sqrt",
                        _unary(helper, "reduce_sum",
                               _unary(helper, "square", param),
                               reduce_all=True))
        g_norm = _unary(helper, "sqrt",
                        _unary(helper, "reduce_sum",
                               _unary(helper, "square", grad),
                               reduce_all=True))
        num = _binary(helper, "elementwise_mul", learning_rate, p_norm)
        if not (isinstance(param_lr, float) and param_lr == 1.0):
            num = _unary(helper, "scale", num, scale=float(param_lr),
                         bias=0.0, bias_after_scale=True)
        decayed_lr = _binary(helper, "elementwise_div", num,
                             _balanced_weight(p_norm, g_norm))
        param.optimize_attr["learning_rate"] = decayed_lr
        decayed.append(decayed_lr)
    return decayed
