"""CNN layers: conv2d/3d, conv2d_transpose, pool2d/3d, batch_norm,
layer_norm, group_norm, lrn, image_resize.

Parity: reference ``python/paddle/fluid/layers/nn.py`` (conv2d:1585,
pool2d, batch_norm, layer_norm, conv2d_transpose, lrn, image_resize) —
same signatures/semantics (NCHW, OIHW filters, groups, fused act), with
the compute re-designed as single XLA ops (see ops/conv.py, ops/pool.py,
ops/norm.py).
"""

from ..framework import Variable
from ..initializer import ConstantInitializer, NormalInitializer
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from ..registry import int_list as _pair

__all__ = [
    "conv2d",
    "conv3d",
    "conv2d_transpose",
    "conv3d_transpose",
    "pool2d",
    "pool3d",
    "batch_norm",
    "layer_norm",
    "group_norm",
    "lrn",
    "image_resize",
    "resize_bilinear",
]



def _conv_nd(nd, op_type, input, num_filters, filter_size, stride, padding,
             dilation, groups, param_attr, bias_attr, use_cudnn, act, name):
    helper = LayerHelper(op_type, input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    if num_channels is not None and num_channels > 0 and \
            num_channels % groups != 0:
        raise ValueError("num_channels must be divisible by groups")

    filter_size = _pair(filter_size, nd)
    stride = _pair(stride, nd)
    padding = _pair(padding, nd)
    dilation = _pair(dilation, nd)

    filter_shape = [num_filters, num_channels // groups] + filter_size
    # reference conv2d default: Normal(0, (2/fan_in)^0.5) MSRA-style
    fan_in = (num_channels // groups) * 1
    for k in filter_size:
        fan_in *= k
    std = (2.0 / fan_in) ** 0.5
    w = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=NormalInitializer(0.0, std),
    )
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type=op_type,
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "use_cudnn": use_cudnn,
        },
    )
    if helper.bias_attr is not None and \
            helper.kwargs.get("bias_attr") is not False:
        pre_act = _channel_bias(helper, pre_bias)
    else:
        pre_act = pre_bias
    return helper.append_activation(pre_act)


def _channel_bias(helper, input_var):
    """Per-output-channel bias on axis 1 (NCHW)."""
    c = input_var.shape[1]
    b = helper.create_parameter(
        attr=helper.bias_attr, shape=[c], dtype=input_var.dtype, is_bias=True
    )
    tmp = helper.create_variable_for_type_inference(dtype=input_var.dtype)
    helper.append_op(
        type="elementwise_add",
        inputs={"X": [input_var], "Y": [b]},
        outputs={"Out": [tmp]},
        attrs={"axis": 1},
    )
    return tmp


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    op = "depthwise_conv2d" if (
        groups and input.shape[1] == groups and groups == num_filters
    ) else "conv2d"
    return _conv_nd(2, op, input, num_filters, filter_size, stride, padding,
                    dilation, groups, param_attr, bias_attr, use_cudnn, act,
                    name)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    return _conv_nd(3, "conv3d", input, num_filters, filter_size, stride,
                    padding, dilation, groups, param_attr, bias_attr,
                    use_cudnn, act, name)


def _conv_transpose_nd(nd, op_type, input, num_filters, output_size,
                       filter_size, padding, stride, dilation, groups,
                       param_attr, bias_attr, use_cudnn, act, name):
    helper = LayerHelper(op_type, input=input,
                         param_attr=param_attr, bias_attr=bias_attr, act=act,
                         name=name)
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    stride = _pair(stride, nd)
    padding = _pair(padding, nd)
    dilation = _pair(dilation, nd)

    if filter_size is None:
        if output_size is None:
            raise ValueError("output_size or filter_size must be set")
        output_size = _pair(output_size, nd)
        filter_size = []
        for i in range(nd):
            in_s = input.shape[2 + i]
            filter_size.append(
                (output_size[i] - (in_s - 1) * stride[i] + 2 * padding[i]
                 - 1) // dilation[i] + 1
            )
    else:
        filter_size = _pair(filter_size, nd)

    filter_shape = [num_channels, num_filters // groups] + filter_size
    w = helper.create_parameter(attr=helper.param_attr, shape=filter_shape,
                                dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type=op_type,
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "use_cudnn": use_cudnn,
        },
    )
    if helper.bias_attr is not None and \
            helper.kwargs.get("bias_attr") is not False:
        pre_act = _channel_bias(helper, pre_bias)
    else:
        pre_act = pre_bias
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    return _conv_transpose_nd(2, "conv2d_transpose", input, num_filters,
                              output_size, filter_size, padding, stride,
                              dilation, groups, param_attr, bias_attr,
                              use_cudnn, act, name)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """Transposed 3-D conv (reference nn.py:conv3d_transpose /
    conv_transpose_op.cc:303)."""
    return _conv_transpose_nd(3, "conv3d_transpose", input, num_filters,
                              output_size, filter_size, padding, stride,
                              dilation, groups, param_attr, bias_attr,
                              use_cudnn, act, name)


def _pool_nd(nd, input, pool_size, pool_type, pool_stride, pool_padding,
             global_pooling, use_cudnn, ceil_mode, exclusive, name):
    if pool_type not in ("max", "avg"):
        raise ValueError("pool_type must be 'max' or 'avg'")
    helper = LayerHelper("pool%dd" % nd, input=input, name=name)
    dtype = helper.input_dtype()
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="pool%dd" % nd,
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": _pair(pool_size, nd),
            "global_pooling": global_pooling,
            "strides": _pair(pool_stride, nd),
            "paddings": _pair(pool_padding, nd),
            "use_cudnn": use_cudnn,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        },
    )
    return out


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    return _pool_nd(2, input, pool_size, pool_type, pool_stride, pool_padding,
                    global_pooling, use_cudnn, ceil_mode, exclusive, name)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    return _pool_nd(3, input, pool_size, pool_type, pool_stride, pool_padding,
                    global_pooling, use_cudnn, ceil_mode, exclusive, name)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False,
               fuse_with_relu=False, use_global_stats=False):
    helper = LayerHelper("batch_norm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    param_shape = [c]

    scale = helper.create_parameter(
        attr=helper.param_attr, shape=param_shape, dtype=dtype,
        default_initializer=ConstantInitializer(1.0),
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=param_shape, dtype=dtype, is_bias=True
    )
    mean = helper.create_parameter(
        attr=ParamAttr(name=moving_mean_name,
                       initializer=ConstantInitializer(0.0), trainable=False),
        shape=param_shape, dtype=dtype)
    variance = helper.create_parameter(
        attr=ParamAttr(name=moving_variance_name,
                       initializer=ConstantInitializer(1.0), trainable=False),
        shape=param_shape, dtype=dtype)
    mean.stop_gradient = True
    variance.stop_gradient = True

    saved_mean = helper.create_variable_for_type_inference(dtype)
    saved_variance = helper.create_variable_for_type_inference(dtype)
    # in_place is accepted for API parity but never aliases: reusing the
    # input name would make the auto-vjp grad re-read the normalized value
    # as X and silently corrupt upstream gradients. XLA buffer-reuses the
    # dead input anyway, so there is no memory win to alias at this level.
    out = helper.create_variable_for_type_inference(dtype)

    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [variance],
                 "SavedMean": [saved_mean],
                 "SavedVariance": [saved_variance]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout,
               "use_global_stats": use_global_stats},
    )
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    param_shape = [1]
    for s in input.shape[begin_norm_axis:]:
        param_shape[0] *= s

    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            attr=helper.param_attr, shape=param_shape, dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=param_shape, dtype=dtype,
            is_bias=True,
        )
        inputs["Bias"] = [b]

    mean_out = helper.create_variable_for_type_inference(dtype)
    var_out = helper.create_variable_for_type_inference(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="layer_norm", inputs=inputs,
        outputs={"Y": [out], "Mean": [mean_out], "Variance": [var_out]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis},
    )
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(
        attr=helper.param_attr, shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1.0),
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[c], dtype=dtype, is_bias=True
    )
    mean_out = helper.create_variable_for_type_inference(dtype)
    var_out = helper.create_variable_for_type_inference(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="group_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias]},
        outputs={"Y": [out], "Mean": [mean_out], "Variance": [var_out]},
        attrs={"epsilon": epsilon, "groups": groups,
               "data_layout": data_layout},
    )
    return helper.append_activation(out)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", input=input, name=name)
    dtype = helper.input_dtype()
    mid = helper.create_variable_for_type_inference(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="lrn", inputs={"X": [input]},
        outputs={"Out": [out], "MidOut": [mid]},
        attrs={"n": n, "k": k, "alpha": alpha, "beta": beta},
    )
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR"):
    resample_methods = {"BILINEAR": "bilinear_interp",
                        "NEAREST": "nearest_interp"}
    if resample not in resample_methods:
        raise ValueError("resample must be BILINEAR or NEAREST")
    if out_shape is None and scale is None:
        raise ValueError("one of out_shape and scale must be set")
    if out_shape is not None:
        if isinstance(out_shape, Variable):
            raise NotImplementedError(
                "dynamic out_shape requires static shapes under XLA"
            )
        out_h, out_w = int(out_shape[0]), int(out_shape[1])
    else:
        out_h = int(input.shape[2] * scale)
        out_w = int(input.shape[3] * scale)
    helper = LayerHelper("image_resize", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type=resample_methods[resample],
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"out_h": out_h, "out_w": out_w},
    )
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, name, "BILINEAR")
