"""Input layers: ``data`` (feed entry points) + the in-program reader
family as HOST-SIDE handles.

Parity: reference ``python/paddle/fluid/layers/io.py:37 data`` — declares a
feedable program input.  ``append_batch_size=True`` prepends a -1 batch dim
like the reference; on TPU the executor specializes the jit per concrete
batch size (bucketing handles variance — see data layer docs).

The reference expresses its input pipeline as ops INSIDE the program
(``open_files_op.cc``, ``create_py_reader_op.cc``,
``create_double_buffer_reader_op.cc``…): reader variables flow through
decorator ops and ``read_file`` unpacks them into tensors.  Under jit
there are no host-side ops mid-graph, so the same surface is served by
``ReaderHandle``: ``py_reader``/``open_files``/``random_data_generator``
build a handle bound to freshly-declared data vars, ``shuffle``/``batch``
decorate its host stream, ``double_buffer`` stages batches onto the
device ahead of the loop (``paddle_tpu.reader.PyReader``), and
``read_file`` returns the data vars the handle feeds.  The training
loop consumes it as ``for feed in handle: exe.run(feed=feed, ...)`` —
the one structural difference from the reference's feed-less
``exe.run()``, stated here rather than papered over.
"""

import numpy as np

from ..core import VarType
from ..framework import default_main_program, default_startup_program

__all__ = ["data", "py_reader", "open_files", "read_file", "shuffle",
           "batch", "double_buffer", "random_data_generator", "load",
           "Preprocessor"]


def data(
    name,
    shape,
    append_batch_size=True,
    dtype="float32",
    lod_level=0,
    type=VarType.DENSE_TENSOR,
    stop_gradient=True,
):
    helper_block = default_main_program().current_block()
    shape = list(shape)
    if lod_level >= 1:
        # padded-batch sequence representation (TPU replacement for LoD):
        # [batch, time, *shape] plus a companion int32 [batch] length var
        # named "<name>@LEN" that DataFeeder fills and sequence ops consume
        shape = [-1, -1] + shape
    elif append_batch_size:
        shape = [-1] + shape
    var = helper_block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        type=type,
        stop_gradient=stop_gradient,
        lod_level=lod_level,
        is_data=True,
    )
    if lod_level >= 1:
        len_var = helper_block.create_var(
            name=name + "@LEN",
            shape=[-1],
            dtype="int32",
            stop_gradient=True,
            is_data=True,
        )
        var._seq_len_name = len_var.name
    return var


# ---------------------------------------------------------------------------
# reader-family handles (see module docstring for the redesign)
# ---------------------------------------------------------------------------

class ReaderHandle(object):
    """Host-side stand-in for the reference's in-program reader
    variable: owns the declared data vars and a host sample stream;
    iterating yields feed dicts for ``Executor.run``."""

    def __init__(self, data_vars, source=None, batched=False, name=None):
        self.data_vars = list(data_vars)
        self._source = source          # callable -> iterator of samples
        self._batched = batched        # True once batch() decorated
        self._tensors = False          # True for tensor-provider sources
        self._dicts = False            # True when source yields feed dicts
        self._place = None             # set by double_buffer
        self._capacity = None
        self.name = name

    # -- decoration (reference decorated-reader chain) ------------------
    def decorate_paddle_reader(self, reader):
        """Attach a sample-tuple reader (will be batched by batch())."""
        self._source = reader
        self._batched = False
        self._tensors = False
        return self

    def decorate_tensor_provider(self, reader):
        """Attach a reader yielding one ALREADY-BATCHED array per slot
        per step (the reference's decorate_tensor_provider contract):
        tuples map positionally onto the data vars, no sample-row
        conversion."""
        self._source = reader
        self._batched = True
        self._tensors = True
        return self

    # -- protocol parity -------------------------------------------------
    def start(self):
        """Reference py_reader.start(): nothing to launch host-side —
        the stream starts when iteration begins."""
        return self

    def reset(self):
        return self

    def _feeder(self):
        from ..data_feeder import DataFeeder
        return DataFeeder(feed_list=self.data_vars)

    def __iter__(self):
        if self._source is None:
            raise RuntimeError(
                "no data source attached: call decorate_paddle_reader "
                "(or build the handle with open_files/"
                "random_data_generator)")
        if not self._batched:
            # The reference's documented usage attaches an ALREADY
            # batched reader — decorate_paddle_reader(paddle.batch(...),
            # reference io.py py_reader docs) — while sample-level
            # sources need layers.batch() applied here.  Sniff the first
            # yield: a batched source yields LISTS of sample rows;
            # accept it directly so reference-ported scripts work
            # unchanged, and keep the clear error for true sample
            # streams (ADVICE r4: the old message sent batched-source
            # users into double-batching).
            probe = iter(self._source())
            try:
                first = next(probe)
            except StopIteration:
                return iter(())
            # strictly lists-of-TUPLES: paddle.batch emits lists whose
            # rows are the sample tuples.  A list-of-lists could equally
            # be ONE sample whose slots are lists, so it keeps the
            # explicit-batch error rather than risking silent
            # mis-batching.
            if isinstance(first, list) and first and \
                    isinstance(first[0], tuple):
                import itertools
                chained = itertools.chain([first], probe)
                batched = self._replace(lambda: chained, batched=True)
                return iter(batched)
            row = type(first[0]).__name__ \
                if isinstance(first, (list, tuple)) and first \
                else type(first).__name__
            raise RuntimeError(
                "cannot tell whether the attached source is batched "
                "(first yield's rows are %r-typed; a batched reader "
                "yields lists of sample TUPLES): apply "
                "fluid.layers.batch(reader, batch_size) for a "
                "sample-level source, or make the batched source yield "
                "lists of tuples (paddle.batch does)" % row)
        if self._dicts:
            def convert(d):
                return d
        elif self._tensors:
            names = [v.name for v in self.data_vars]

            def convert(tensors):
                if len(tensors) != len(names):
                    raise ValueError(
                        "tensor provider yielded %d arrays for %d slots"
                        % (len(tensors), len(names)))
                return dict(zip(names, (np.asarray(t) for t in tensors)))
        else:
            feeder = self._feeder()
            convert = feeder.feed
        if self._place is not None:
            from ..reader import DevicePrefetcher

            class _F:
                def feed(self, rows, _convert=convert):
                    return _convert(rows)

            return iter(DevicePrefetcher(
                self._source, feeder=_F(), place=self._place,
                capacity=self._capacity or 4))
        return (convert(rows) for rows in self._source())

    def _replace(self, source, batched=None):
        h = ReaderHandle(self.data_vars, source,
                         self._batched if batched is None else batched,
                         self.name)
        h._place, h._capacity = self._place, self._capacity
        h._tensors = self._tensors
        h._dicts = self._dicts
        return h


def _declare_reader_vars(shapes, dtypes, lod_levels, name,
                         shapes_include_batch=True):
    from .. import unique_name
    lod_levels = lod_levels or [0] * len(shapes)
    vars_ = []
    for i, (shp, dt, ll) in enumerate(zip(shapes, dtypes, lod_levels)):
        # py_reader/open_files shapes include the batch dim (reference
        # contract); strip it — data() re-prepends -1 — keeping inner
        # -1 dims (variable time steps) so the rank survives.
        # random_data_generator shapes are per-sample (batch-free).
        if shapes_include_batch:
            shp = list(shp[1:]) if shp else []
        else:
            shp = list(shp)
        vars_.append(data(
            unique_name.generate("%s_slot%d" % (name or "reader", i)),
            shape=list(shp), dtype=dt, lod_level=ll))
    return vars_


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Python-fed input pipeline (reference io.py:473 py_reader /
    create_py_reader_op.cc): declares one data var per slot and returns
    the handle; attach a sample stream with decorate_paddle_reader."""
    handle = ReaderHandle(
        _declare_reader_vars(shapes, dtypes, lod_levels, name), name=name)
    handle._capacity = capacity
    if use_double_buffer:
        # the reference stages to the device by default; TPUPlace falls
        # back to the first local device on CPU-only hosts
        from ..executor import TPUPlace
        handle._place = TPUPlace(0)
    return handle


def open_files(filenames, shapes, lod_levels, dtypes, thread_num=1,
               buffer_size=None, pass_num=1, for_parallel=True):
    """Multi-file parallel reader (reference io.py:721 /
    open_files_op.cc): recordio files scanned by ``thread_num`` worker
    processes; samples are pickled tuples as recordio_writer wrote
    them."""
    from ..reader.creator import open_recordio_files
    handle = ReaderHandle(
        _declare_reader_vars(shapes, dtypes, lod_levels, "open_files"))
    src = open_recordio_files(
        list(filenames), num_workers=max(1, thread_num),
        prefetch=buffer_size or 256, repeat=False)
    if pass_num > 1:
        base = src

        def multi_pass():
            for _ in range(pass_num):
                for s in base():
                    yield s
        src = multi_pass
    handle._source = src
    handle._batched = False
    return handle


def random_data_generator(low, high, shapes, lod_levels=None,
                          for_parallel=True):
    """Uniform-random synthetic reader (reference io.py /
    create_random_data_generator_op.cc) — benchmarking without IO."""
    handle = ReaderHandle(
        _declare_reader_vars(shapes, ["float32"] * len(shapes),
                             lod_levels, "rand",
                             shapes_include_batch=False))
    # reference contract: shapes are PER-SAMPLE (no batch dim); a random
    # generator cannot invent variable (-1) extents
    dims = [list(shp) or [1] for shp in shapes]
    for shp, d in zip(shapes, dims):
        if any(x == -1 for x in d):
            raise ValueError(
                "random_data_generator needs concrete per-sample dims, "
                "got %s" % (tuple(shp),))

    def src():
        rng = np.random.RandomState(0)
        while True:
            yield tuple(rng.uniform(low, high, size=d).astype("float32")
                        for d in dims)
    handle._source = src
    handle._batched = False
    return handle


def read_file(reader):
    """Unpack a reader handle into its data vars (reference io.py:888
    read_file / read_op)."""
    if isinstance(reader, Preprocessor):
        reader = reader()
    if not isinstance(reader, ReaderHandle):
        raise TypeError("read_file expects a reader handle from "
                        "py_reader/open_files/random_data_generator "
                        "(or a built Preprocessor)")
    if len(reader.data_vars) == 1:
        return reader.data_vars[0]
    return list(reader.data_vars)


def shuffle(reader, buffer_size):
    """Shuffle decorator over a reader handle (reference io.py shuffle /
    create_shuffle_reader_op.cc)."""
    from ..reader import shuffle as _shuffle
    if reader._source is None:
        raise RuntimeError("attach a source before shuffle()")
    return reader._replace(_shuffle(reader._source, buffer_size))


def batch(reader, batch_size):
    """Batch decorator over a reader handle (reference io.py batch /
    create_batch_reader_op.cc)."""
    from ..reader import batch as _batch
    if reader._source is None:
        raise RuntimeError("attach a source before batch()")
    return reader._replace(_batch(reader._source, batch_size),
                           batched=True)


def double_buffer(reader, place=None, name=None, capacity=None):
    """Stage batches onto the device ahead of the consuming loop
    (reference io.py:888 double_buffer /
    create_double_buffer_reader_op.cc — here via
    reader.DevicePrefetcher's daemon device_put thread; ``capacity``
    widens the classic 2-deep double buffer into an N-deep window)."""
    if isinstance(reader, Preprocessor):
        reader = reader()
    h = reader._replace(reader._source)
    from ..executor import TPUPlace
    # default: the accelerator (TPUPlace falls back to the first local
    # device on CPU-only hosts) — staging to CPU would just add a copy
    h._place = place or TPUPlace(0)
    if capacity is not None:
        h._capacity = capacity
    return h


def load(out, file_path, load_as_fp16=None):
    """Load a saved variable into ``out`` (reference io.py load /
    load_op.cc).  Reads the ``io.save_vars`` per-var ``.npy`` file at
    graph-build time and emits an assign of the literal — the
    deployment-parity path for programs that load weights mid-graph."""
    arr = np.load(file_path if file_path.endswith(".npy")
                  else file_path + ".npy")
    if load_as_fp16:
        arr = arr.astype(np.float16)
    from .tensor import assign
    return assign(arr.astype(out.dtype or arr.dtype), output=out)


class Preprocessor(object):
    """Per-batch preprocessing block over a reader handle (reference
    io.py Preprocessor / create_custom_reader_op.cc: a sub-block of ops
    runs on every batch).  The block is built as its OWN small Program
    and executed per batch on the host CPU backend; the handle then
    yields the transformed feeds."""

    def __init__(self, reader, name=None):
        if not isinstance(reader, ReaderHandle):
            raise TypeError("Preprocessor wraps a reader handle")
        self.underlying = reader
        self.name = name
        self._program = None
        self._startup = None
        self._in_vars = None
        self._out_vars = None
        self.sub_reader = None

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def _cm():
            from ..framework import Program, program_guard
            self._program, self._startup = Program(), Program()
            with program_guard(self._program, self._startup):
                yield self
            if self._out_vars is None:
                raise RuntimeError("Preprocessor block set no outputs()")
            self._build()
        return _cm()

    def inputs(self):
        from .. import unique_name
        if self._in_vars is None:
            self._in_vars = [
                data(unique_name.generate("prep_in"),
                     shape=list(v.shape[1:]), dtype=v.dtype)
                for v in self.underlying.data_vars
            ]
        return list(self._in_vars)

    def outputs(self, *outs):
        self._out_vars = list(outs)

    def _build(self):
        from ..executor import CPUPlace, Executor
        if len(self._out_vars) != len(self.underlying.data_vars):
            raise ValueError(
                "Preprocessor block produced %d outputs for a %d-slot "
                "reader; outputs() must map one-to-one onto the "
                "underlying slots" % (len(self._out_vars),
                                      len(self.underlying.data_vars)))
        exe = Executor(CPUPlace())
        exe.run(self._startup)
        prog, ins, outs = self._program, self._in_vars, self._out_vars
        under = self.underlying

        def prep_source():
            for feed in iter(under):
                renamed = {iv.name: feed[dv.name]
                           for iv, dv in zip(ins, under.data_vars)}
                res = exe.run(prog, feed=renamed,
                              fetch_list=outs, return_numpy=True)
                yield {dv.name: np.asarray(r) for dv, r
                       in zip(under.data_vars, res)}

        # a plain handle whose SOURCE yields preprocessed feed dicts:
        # survives _replace, so double_buffer(preprocessor()) keeps the
        # preprocessing (ADVICE r4)
        self.sub_reader = ReaderHandle(under.data_vars,
                                       source=prep_source, batched=True)
        self.sub_reader._dicts = True

    def __iter__(self):
        if self.sub_reader is None:
            raise RuntimeError("build the Preprocessor block first")
        return iter(self.sub_reader)

    def __call__(self):
        """Reference idiom parity (ADVICE r4): ``preprocessor()``
        returns the decorated reader handle, so
        ``double_buffer(preprocessor())`` / ``read_file(preprocessor)``
        both work."""
        if self.sub_reader is None:
            raise RuntimeError("build the Preprocessor block first")
        return self.sub_reader
