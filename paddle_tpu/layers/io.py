"""Input layers: ``data`` (feed entry points).

Parity: reference ``python/paddle/fluid/layers/io.py:37 data`` — declares a
feedable program input.  ``append_batch_size=True`` prepends a -1 batch dim
like the reference; on TPU the executor specializes the jit per concrete
batch size (bucketing handles variance — see data layer docs).
py_reader / double_buffer equivalents live in ``paddle_tpu.reader``
(``PyReader``: host thread staging feed dicts onto the device ahead of
the training loop).
"""

from ..core import VarType
from ..framework import default_main_program, default_startup_program

__all__ = ["data"]


def data(
    name,
    shape,
    append_batch_size=True,
    dtype="float32",
    lod_level=0,
    type=VarType.DENSE_TENSOR,
    stop_gradient=True,
):
    helper_block = default_main_program().current_block()
    shape = list(shape)
    if lod_level >= 1:
        # padded-batch sequence representation (TPU replacement for LoD):
        # [batch, time, *shape] plus a companion int32 [batch] length var
        # named "<name>@LEN" that DataFeeder fills and sequence ops consume
        shape = [-1, -1] + shape
    elif append_batch_size:
        shape = [-1] + shape
    var = helper_block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        type=type,
        stop_gradient=stop_gradient,
        lod_level=lod_level,
        is_data=True,
    )
    if lod_level >= 1:
        len_var = helper_block.create_var(
            name=name + "@LEN",
            shape=[-1],
            dtype="int32",
            stop_gradient=True,
            is_data=True,
        )
        var._seq_len_name = len_var.name
    return var
