"""Control-plane RPC for the elastic master: TCP server + client.

Parity: the Go master is a net/rpc service discovered via etcd
(``go/master/service.go``, ``go/master/client.go``) and consumed from
Python through cgo bindings (``python/paddle/v2/master/client.py:29``).
Here the transport is newline-delimited JSON over TCP — control-plane
only (task leases, barriers, save-model votes); all tensor traffic
stays on ICI/DCN via XLA collectives, so a heavyweight RPC stack buys
nothing.

The client retries with backoff on connection failures, mirroring the
Go client's reconnect-on-error loop: a trainer that outlives a master
restart keeps working as long as the new master recovered from the same
Store.
"""

import json
import random
import socket
import socketserver
import threading
import time

from ..monitor import tracing
from .master import (AllTasksFailed, NoMoreAvailable, PassAfter,
                     PassBefore, Task)

__all__ = ["MasterServer", "MasterClient", "service_methods"]

_ERRORS = {
    "PassBefore": PassBefore,
    "PassAfter": PassAfter,
    "NoMoreAvailable": NoMoreAvailable,
    "AllTasksFailed": AllTasksFailed,
}


# the MasterService surface (the pre-cluster hardcoded dispatch set);
# services exposing ``rpc_methods()`` override it — the ClusterMaster
# rides the same server/handler by listing its own methods
_DEFAULT_METHODS = ("get_task", "task_finished", "task_failed",
                    "request_save_model", "set_dataset", "stats")


def service_methods(svc):
    """{name: bound method} the server is allowed to dispatch: the
    service's own ``rpc_methods()`` list when it has one, else the
    MasterService default set.  An explicit allowlist — a generic
    getattr dispatch would export every public method of whatever
    object the server wraps."""
    lister = getattr(svc, "rpc_methods", None)
    names = tuple(lister()) if callable(lister) else _DEFAULT_METHODS
    return {n: getattr(svc, n) for n in names}


def _jsonable(result):
    """Marshal a service return value: objects exposing ``to_dict``
    (Task, cluster records) flatten; JSON-native values pass through."""
    to_dict = getattr(result, "to_dict", None)
    return to_dict() if callable(to_dict) else result


# per-method RPC latency histograms (``rpc/<method>_seconds``), handles
# cached against the registry generation like every monitor producer —
# a cluster reconnect storm shows up in the same exposition as the
# requests it delays
_rpc_hists = {}
_rpc_gen = [-1]

# request payload sizes (``rpc/<method>_request_bytes``): byte-shaped
# buckets, not the latency-shaped defaults — the fleet telemetry digest
# rides the heartbeat envelope, and this histogram is the wire-side
# check that it stays inside FLAGS_fleet_digest_bytes
_RPC_BYTE_BUCKETS = (64.0, 256.0, 1024.0, 4096.0, 16384.0, 65536.0,
                     262144.0)


def _observe_rpc(method, seconds, request_bytes=None):
    from .. import monitor

    if not monitor.enabled():
        return
    reg = monitor.registry()
    if _rpc_gen[0] != reg.generation:
        _rpc_hists.clear()
        _rpc_gen[0] = reg.generation
    h = _rpc_hists.get(method)
    if h is None:
        h = _rpc_hists[method] = reg.histogram(
            "rpc/%s_seconds" % method)
    h.observe(seconds)
    if request_bytes is not None:
        key = method + "/bytes"
        hb = _rpc_hists.get(key)
        if hb is None:
            hb = _rpc_hists[key] = reg.histogram(
                "rpc/%s_request_bytes" % method,
                buckets=_RPC_BYTE_BUCKETS)
        hb.observe(float(request_bytes))


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        methods = self.server.methods
        while True:
            line = self.rfile.readline()
            if not line:
                return
            span = None
            try:
                req = json.loads(line.decode("utf-8"))
                method = req["method"]
                args = req.get("args", [])
                # the envelope's trace context makes the server-side
                # span a CHILD of the caller's rpc span: this process's
                # JSONL joins the caller's tree at assembly time
                if tracing.enabled() and req.get("trace"):
                    span = tracing.server_span(method, req["trace"])
                    # open-anchor NOW: a handler killed mid-call (a
                    # fleet replica SIGKILLed mid-generate) must leave
                    # its already-flushed child spans — the engine's
                    # request anchor, queue_wait — linked under a
                    # resolvable parent, or the caller's otherwise
                    # terminal tree assembles INCOMPLETE
                    span.emit_open()
                if method == "ping":
                    resp = {"ok": True, "result": "pong"}
                elif method in methods:
                    # dispatch UNDER the server span (thread-local
                    # current): spans the service creates — a fleet
                    # route decision, a replica-side request tree —
                    # parent to this RPC leg and join the caller's
                    # cross-process trace
                    with tracing.use_span(span):
                        resp = {"ok": True,
                                "result": _jsonable(
                                    methods[method](*args))}
                else:
                    resp = {"ok": False, "error": "Unknown",
                            "message": f"no method {method!r}"}
            except tuple(_ERRORS.values()) as e:
                resp = {"ok": False, "error": type(e).__name__,
                        "message": str(e)}
            except Exception as e:  # noqa: BLE001 — marshalled to client
                resp = {"ok": False, "error": "RuntimeError",
                        "message": f"{type(e).__name__}: {e}"}
            if span is not None:
                span.finish("ok" if resp.get("ok") else "error")
            self.wfile.write(json.dumps(resp).encode("utf-8") + b"\n")
            self.wfile.flush()


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class MasterServer:
    """Serve a MasterService on host:port in background threads."""

    def __init__(self, service, host="127.0.0.1", port=0):
        self.service = service
        self._srv = _TCPServer((host, port), _Handler)
        self._srv.service = service
        self._srv.methods = service_methods(service)
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    @property
    def address(self):
        host, port = self._srv.server_address[:2]
        return f"{host}:{port}"

    def start(self):
        self._thread.start()
        return self

    def shutdown(self):
        self._srv.shutdown()
        self._srv.server_close()


class MasterClient:
    """Trainer-side client (python/paddle/v2/master/client.py parity).

    ``get_task``/``task_finished``/``task_failed``/``request_save_model``
    mirror the cgo client's surface; transient socket errors trigger
    reconnect+retry so trainers ride out master restarts.  The retry
    loop backs off EXPONENTIALLY with jitter (``retry_interval`` doubles
    per failure up to ``max_retry_interval``, each sleep stretched by up
    to ``jitter``x) so a restarting master is not hammered by a
    thundering herd of fixed-cadence trainers, and the budget is
    bounded: after ``max_retries`` failed attempts a ``ConnectionError``
    names the endpoint, the attempt count, and the last error instead
    of retrying forever.  Each reconnect attempt after a failure counts
    into the ``master/reconnects`` monitor counter.
    """

    def __init__(self, address, timeout=30.0, retry_interval=0.2,
                 max_retries=12, max_retry_interval=5.0, jitter=0.5):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout
        self._retry = float(retry_interval)
        self._max_retries = max(1, int(max_retries))
        self._max_retry_interval = float(max_retry_interval)
        self._jitter = max(0.0, float(jitter))
        self._sock = None
        self._file = None
        self._mu = threading.Lock()

    def _connect(self):
        self.close()
        self._sock = socket.create_connection(self._addr,
                                              timeout=self._timeout)
        self._file = self._sock.makefile("rwb")

    def _call(self, method, *args):
        from .. import monitor

        endpoint = "%s:%d" % self._addr
        # client-leg rpc span: parents to the thread's current span
        # (barrier/heartbeat sessions) and rides the envelope so the
        # server's span joins the same tree
        span = (tracing.client_span(method, endpoint)
                if tracing.enabled() else None)
        t0 = time.perf_counter()
        with self._mu:
            last_err = None
            delay = self._retry
            slept = 0.0
            for attempt in range(self._max_retries):
                try:
                    if self._file is None:
                        if attempt > 0:
                            monitor.count("master/reconnects")
                        self._connect()
                    envelope = {"method": method, "args": list(args)}
                    if span is not None:
                        envelope["trace"] = span.context()
                    payload = json.dumps(envelope)
                    self._file.write(payload.encode("utf-8") + b"\n")
                    self._file.flush()
                    line = self._file.readline()
                    if not line:
                        raise ConnectionError("master closed connection")
                    resp = json.loads(line.decode("utf-8"))
                    if resp["ok"]:
                        if span is not None:
                            span.finish("ok", attempts=attempt + 1)
                        _observe_rpc(method, time.perf_counter() - t0,
                                     request_bytes=len(payload))
                        return resp["result"]
                    exc = _ERRORS.get(resp["error"], RuntimeError)
                    err = exc(resp.get("message", ""))
                    if span is not None:
                        span.finish("error", attempts=attempt + 1,
                                    error=type(err).__name__)
                    raise err
                except (OSError, ConnectionError, json.JSONDecodeError) \
                        as e:
                    last_err = e
                    self.close()
                    if attempt == self._max_retries - 1:
                        break       # budget spent: no trailing sleep
                    if span is not None:
                        # one marker per reconnect attempt: a storm is
                        # visible in the same JSONL as the requests and
                        # barriers it delays
                        span.event("rpc_retry", status="error",
                                   attrs={"method": method,
                                          "endpoint": endpoint,
                                          "attempt": attempt + 1,
                                          "backoff_s": round(delay, 3)})
                    # full-jitter exponential backoff: sleep in
                    # [delay, delay*(1+jitter)], then double toward the
                    # cap — decorrelates a herd of reconnecting trainers
                    time.sleep(delay * (1.0 + random.random()
                                        * self._jitter))
                    slept += delay
                    delay = min(delay * 2.0, self._max_retry_interval)
            if span is not None:
                span.finish("error", attempts=self._max_retries,
                            error="unreachable")
            raise ConnectionError(
                "master at %s:%d unreachable after %d attempts (~%.1fs "
                "of backoff); last error: %r — check the master "
                "endpoint or raise max_retries" %
                (self._addr[0], self._addr[1], self._max_retries, slept,
                 last_err))

    def call(self, method, *args):
        """Generic RPC (the cluster runtime's transport hook): invokes
        any method the served service allowlists via ``rpc_methods()``,
        with the same reconnect/backoff behavior as the typed calls."""
        return self._call(method, *args)

    def get_task(self, pass_id=None):
        return Task.from_dict(self._call("get_task", pass_id))

    def task_finished(self, task_id, epoch=None):
        self._call("task_finished", task_id, epoch)

    def task_failed(self, task_id, epoch):
        self._call("task_failed", task_id, epoch)

    def request_save_model(self, trainer_id, block_secs):
        return self._call("request_save_model", trainer_id, block_secs)

    def set_dataset(self, chunks):
        self._call("set_dataset", chunks)

    def stats(self):
        return self._call("stats")

    def ping(self):
        return self._call("ping")

    def close(self):
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
