"""Snapshot stores for the elastic master — the etcd analog.

Parity: ``go/master/etcd_client.go`` (etcd-backed Save/Load under a
leader lock) and ``go/master/inmem_store.go`` (in-memory Save/Load used
by the Go unit tests).  Here the durable variant is a file with an
atomic rename, which is what a single-coordinator TPU job actually
needs; swapping in a real etcd/consul client only requires implementing
``save``/``load``.
"""

import os
import tempfile
import threading

__all__ = ["InMemStore", "FileStore", "fsync_dir"]


class InMemStore:
    """go/master/inmem_store.go parity: process-local snapshot buffer."""

    def __init__(self):
        self._buf = None
        self._mu = threading.Lock()

    def save(self, data: bytes):
        with self._mu:
            self._buf = data

    def load(self):
        with self._mu:
            return self._buf

    def shutdown(self):
        pass


def fsync_dir(path):
    """Flush the directory entry itself: an atomic rename is only
    durable once the DIRECTORY that holds it is synced.  The one shared
    commit-idiom helper (``parallel/checkpoint.py`` aliases it — this
    module is the dependency-light home; checkpoint importing cloud
    keeps the layering, cloud importing the jax-heavy checkpoint module
    would not)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:       # platforms without directory fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


_fsync_dir = fsync_dir      # module-internal spelling


class FileStore:
    """Durable snapshot store: atomic-rename file writes.

    The recovery contract matches ``go/master/service.go:166`` — a new
    master process constructed over the same store resumes the previous
    master's state (current pass, pending leases, failure counts).
    """

    def __init__(self, path):
        self.path = str(path)
        self._mu = threading.Lock()

    def save(self, data: bytes):
        with self._mu:
            d = os.path.dirname(self.path) or "."
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, prefix=".master_snap_")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(data)
                    f.flush()
                    # fsync the payload BEFORE the rename and the
                    # directory AFTER it: os.replace alone is atomic
                    # against concurrent readers but not against power
                    # loss — an unsynced rename can commit a torn
                    # snapshot, which a recovering master would then
                    # trust as the run's task-lease state
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
                _fsync_dir(d)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)

    def load(self):
        with self._mu:
            if not os.path.exists(self.path):
                return None
            with open(self.path, "rb") as f:
                return f.read()

    def shutdown(self):
        pass
