"""master_reader: a reader decorator that pulls data through the
elastic master's task-lease queue.

Parity: the v2 ``cloud_reader`` pattern — trainers are stateless task
consumers that lease record chunks from the master and report
completion/failure (``python/paddle/v2/master/client.py``,
``go/master/service.go:368 GetTask``).  A trainer that dies mid-task
simply never reports; the lease times out and another trainer re-reads
the same chunks, giving at-least-once (exactly-once-ish across passes)
sample delivery.
"""

from .master import AllTasksFailed, NoMoreAvailable, PassAfter, PassBefore

__all__ = ["master_reader"]


def master_reader(client, chunk_reader, pass_id=None, wait=0.05,
                  max_waits=2000):
    """Build a sample reader over leased tasks.

    ``client``: a MasterClient (or MasterService — same surface).
    ``chunk_reader(chunk) -> iterable of samples`` materializes one
    opaque chunk descriptor.  The reader ends when the master rolls to
    the next pass (PassBefore) or the pass's data is exhausted.
    ``pass_id=None`` reads exactly the master's *current* pass (queried
    at iteration start) — without pinning a pass the rollover would
    refill todo and the reader would re-yield the dataset forever.
    """
    import time as _time

    def reader():
        waits = 0
        pid = pass_id if pass_id is not None else \
            client.stats()["cur_pass"]
        consumed = 0
        while True:
            try:
                task = client.get_task(pid)
            except PassBefore:
                if pass_id is None and consumed == 0:
                    # the pass rolled between our stats() probe and the
                    # first lease: re-pin to the new current pass rather
                    # than silently yielding an empty epoch
                    pid = client.stats()["cur_pass"]
                    continue
                return
            except AllTasksFailed:
                return
            except (NoMoreAvailable, PassAfter):
                # other trainers hold the remaining leases: wait for
                # either a timeout-requeue or the pass rollover
                waits += 1
                if waits > max_waits:
                    return
                _time.sleep(wait)
                continue
            waits = 0
            consumed += 1
            try:
                for chunk in task.chunks:
                    for sample in chunk_reader(chunk):
                        yield sample
            except GeneratorExit:
                raise
            except Exception:
                client.task_failed(task.task_id, task.epoch)
                raise
            client.task_finished(task.task_id, task.epoch)

    return reader
