"""Elastic training coordinator — the TPU rebuild of the reference's Go
cloud layer (``go/master/service.go``, ``go/pserver/service.go`` and the
Python client ``python/paddle/v2/master/client.py``).

Capabilities reproduced (SURVEY.md §2.4 "Go cloud layer", §5 failure
recovery):

* task-lease queue over data shards: todo/pending/done/failed queues,
  timeout requeue, ``failure_max`` discard (``go/master/service.go:140``,
  ``:341 checkTimeoutFunc``, ``:455 TaskFailed``, ``:313
  processFailedTask``);
* state snapshot/recover through a pluggable Store — the etcd analog
  (``go/master/service.go:207 snapshot``, ``:166 recover``);
* checkpoint/save-model arbitration so exactly one live trainer saves
  (``go/master/service.go:481 RequestSaveModel``,
  ``python/paddle/v2/master/client.py:38-56``);
* a host-side TCP service + client for multi-process jobs — the gRPC
  master service analog; collectives stay on ICI/DCN via XLA, this is
  control-plane only.

TPU-first redesign notes: timeouts are *persisted deadlines* checked
lazily under the service lock instead of in-flight goroutine timers, so
a recovered master (new process, old Store) keeps honoring leases the
dead master granted — the reference loses its ``time.AfterFunc`` timers
on restart.
"""

from .master import (  # noqa: F401
    MasterService,
    Task,
    NoMoreAvailable,
    PassBefore,
    PassAfter,
    AllTasksFailed,
    partition,
)
from .store import InMemStore, FileStore  # noqa: F401
from .server import MasterServer, MasterClient  # noqa: F401
from .reader import master_reader  # noqa: F401

__all__ = [
    "MasterService", "Task", "partition",
    "NoMoreAvailable", "PassBefore", "PassAfter", "AllTasksFailed",
    "InMemStore", "FileStore", "MasterServer", "MasterClient",
    "master_reader",
]
