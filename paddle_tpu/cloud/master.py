"""The elastic master's task-lease state machine.

Parity spec: ``go/master/service.go`` —

* ``masterState`` (``:80``): Todo / Pending / Done / Failed + CurPass;
* ``partition`` (``:106``): chunks -> tasks of ``chunks_per_task``;
* ``GetTask`` (``:368``): pass-count handshake (ErrPassBefore /
  ErrPassAfter / ErrNoMoreAvailable / ErrAllTaskFailed), lease with
  timeout, epoch bump per dispatch;
* ``TaskFinished`` (``:411``): done queue, pass rollover when todo and
  pending drain (failed tasks are re-queued for the next pass);
* ``TaskFailed`` (``:455``) / ``processFailedTask`` (``:313``): requeue
  up to ``failure_max`` then discard to Failed;
* ``checkTimeoutFunc`` (``:341``): lease timeout requeue, guarded by the
  task's dispatch epoch so a stale timeout can't kill a fresh lease;
* ``RequestSaveModel`` (``:481``): single-saver arbitration with a
  blocking window.

TPU-first redesign: deadlines live *in the snapshotted state* and are
enforced lazily under the lock (`_expire_stale`), so recovery from the
Store preserves live leases AND their timeouts; the Go original re-arms
nothing after recovery.  Chunks are opaque JSON values (file spans,
recordio chunk descriptors, shard indices) rather than recordio-only.
"""

import json
import threading
import time

__all__ = ["MasterService", "Task", "partition", "NoMoreAvailable",
           "PassBefore", "PassAfter", "AllTasksFailed"]


class PassBefore(Exception):
    """Client's pass is behind the master's (go ErrPassBefore)."""


class PassAfter(Exception):
    """Client ran ahead of the master's pass (go ErrPassAfter): wait."""


class NoMoreAvailable(Exception):
    """Todo drained but pending leases outstanding (go ErrNoMoreAvailable)."""


class AllTasksFailed(Exception):
    """Every task of the pass is in Failed (go ErrAllTaskFailed)."""


class Task:
    """A leased unit of work: a list of opaque chunks + lease metadata.

    Mirrors go ``Task{Meta{ID, Epoch}, Chunks}``.
    """

    __slots__ = ("task_id", "epoch", "chunks", "num_failure", "deadline")

    def __init__(self, task_id, chunks, epoch=0, num_failure=0,
                 deadline=0.0):
        self.task_id = task_id
        self.epoch = epoch
        self.chunks = list(chunks)
        self.num_failure = num_failure
        self.deadline = deadline

    def to_dict(self):
        return {"task_id": self.task_id, "epoch": self.epoch,
                "chunks": self.chunks, "num_failure": self.num_failure,
                "deadline": self.deadline}

    @classmethod
    def from_dict(cls, d):
        return cls(d["task_id"], d["chunks"], d["epoch"],
                   d["num_failure"], d["deadline"])

    def __repr__(self):
        return (f"Task(id={self.task_id}, epoch={self.epoch}, "
                f"chunks={len(self.chunks)}, failures={self.num_failure})")


def partition(chunks, chunks_per_task=1):
    """Group chunks into tasks (go/master/service.go:106).

    IDs are dense ints, and that is CORRECT here — deterministic and
    snapshot-friendly — because uniqueness only has to hold within a
    dataset (``set_dataset`` runs once per job; pass rollover recycles
    the same Task objects, never re-partitions).  The collision the Go
    original's time+rand ids papered over is CROSS-DISPATCH staleness:
    a timed-out holder's late report arriving after the same id was
    re-leased.  That is disambiguated by ``Task.epoch``, which
    increments on every dispatch and guards both ``task_failed`` and
    ``task_finished`` — a stale-epoch report is ignored, exactly the
    miss a random per-dispatch id would have produced, without
    sacrificing determinism."""
    if chunks_per_task <= 0:
        chunks_per_task = 1
    return [Task(i // chunks_per_task, chunks[i:i + chunks_per_task])
            for i in range(0, len(chunks), chunks_per_task)]


class MasterService:
    """Single-coordinator task-lease service (go/master/service.go:140)."""

    def __init__(self, store=None, chunks_per_task=1, timeout=60.0,
                 failure_max=3, clock=time.time, ready_timeout=10.0):
        # NOTE: the clock must be WALL time, not monotonic — lease
        # deadlines are persisted in the snapshot and must stay
        # comparable after a master restart on a rebooted/different host
        from .store import InMemStore

        self.store = store or InMemStore()
        self.chunks_per_task = chunks_per_task
        self.timeout = timeout
        self.failure_max = failure_max
        self._clock = clock
        self._ready_timeout = ready_timeout
        self._mu = threading.RLock()
        self._ready = threading.Event()

        # masterState (go :80)
        self.todo = []
        self.pending = {}          # task_id -> Task
        self.done = []
        self.failed = []
        self.cur_pass = 0

        # transient, like go's savingTrainer (go :101)
        self._saving_trainer = ""
        self._saving_until = 0.0

        snap = self.store.load()
        if snap:
            self._restore(snap)
            self._ready.set()

    # -- snapshot / recover (go :207 snapshot, :166 recover) ------------
    def _snapshot(self):
        state = {
            "todo": [t.to_dict() for t in self.todo],
            "pending": {str(k): v.to_dict() for k, v in
                        self.pending.items()},
            "done": [t.to_dict() for t in self.done],
            "failed": [t.to_dict() for t in self.failed],
            "cur_pass": self.cur_pass,
        }
        self.store.save(json.dumps(state).encode("utf-8"))

    def _restore(self, blob):
        state = json.loads(blob.decode("utf-8"))
        self.todo = [Task.from_dict(d) for d in state["todo"]]
        self.pending = {int(k): Task.from_dict(v)
                        for k, v in state["pending"].items()}
        self.done = [Task.from_dict(d) for d in state["done"]]
        self.failed = [Task.from_dict(d) for d in state["failed"]]
        self.cur_pass = state["cur_pass"]

    # -- dataset registration (go SetDataset :270) ----------------------
    def set_dataset(self, chunks):
        """Register the job's chunk list.  Idempotent after recovery:
        if a snapshot already restored state, later set_dataset calls
        are no-ops (go: initDone guard)."""
        with self._mu:
            if self._ready.is_set():
                return
            self.todo = partition(chunks, self.chunks_per_task)
            self._snapshot()
            self._ready.set()

    @property
    def ready(self):
        return self._ready.is_set()

    # -- lease lifecycle ------------------------------------------------
    def _expire_stale(self):
        """Lazy lease-timeout sweep (replaces go's AfterFunc timers,
        :341).  Must hold the lock."""
        now = self._clock()
        expired = [t for t in self.pending.values() if t.deadline <= now]
        for t in expired:
            self._process_failed(t, t.epoch)

    def _process_failed(self, t, epoch):
        """go processFailedTask (:313).  Must hold the lock."""
        cur = self.pending.get(t.task_id)
        if cur is None or cur.epoch != epoch:
            return  # stale report: the lease was re-dispatched since
        del self.pending[t.task_id]
        t.num_failure += 1
        if t.num_failure > self.failure_max:
            self.failed.append(t)
            # the discard may drain the pass (e.g. the last pending
            # lease died for good while other tasks already finished);
            # without this the job would spin in NoMoreAvailable forever
            self._maybe_roll_pass()
        else:
            self.todo.append(t)
        self._snapshot()

    def _maybe_roll_pass(self):
        """Pass rollover when todo+pending drain (go TaskFinished :427).
        Must hold the lock."""
        if not self.todo and not self.pending and self.done:
            self.cur_pass += 1
            self.todo = self.done + self.failed
            self.done = []
            self.failed = []

    def get_task(self, pass_id=None):
        """Lease the next task (go GetTask :368).

        ``pass_id`` is the client's pass counter; None skips the
        handshake (single-pass jobs).

        Blocks until ``set_dataset`` runs (go GetTask waits on
        ``<-s.ready``), bounded by ``ready_timeout`` so a misconfigured
        job errors instead of hanging trainer threads forever."""
        if not self._ready.wait(timeout=self._ready_timeout):
            raise RuntimeError("dataset not set; call set_dataset first")
        with self._mu:
            self._expire_stale()
            if pass_id is not None:
                if pass_id < self.cur_pass:
                    raise PassBefore(
                        f"client pass {pass_id} < master {self.cur_pass}")
                if pass_id > self.cur_pass:
                    raise PassAfter(
                        f"client pass {pass_id} > master {self.cur_pass}")
            if not self.todo:
                if not self.done and not self.pending:
                    raise AllTasksFailed("all tasks of this pass failed")
                raise NoMoreAvailable("todo drained; leases outstanding")
            t = self.todo.pop(0)
            t.epoch += 1
            t.deadline = self._clock() + self.timeout
            self.pending[t.task_id] = t
            self._snapshot()
            return Task(t.task_id, t.chunks, t.epoch, t.num_failure,
                        t.deadline)

    def task_finished(self, task_id, epoch=None):
        """go TaskFinished (:411); rolls the pass when drained.

        ``epoch`` (the lease's dispatch counter) guards against the
        dense-id staleness hole: a holder whose lease timed out reports
        finished AFTER the task was re-dispatched under the same id —
        without the guard that report would mark the NEW holder's lease
        done and clear it while that holder is still working.  ``None``
        skips the check (pre-guard callers)."""
        with self._mu:
            self._expire_stale()
            t = self.pending.get(task_id)
            if t is None:
                return  # late report after timeout requeue: ignore
            if epoch is not None and t.epoch != epoch:
                return  # stale holder: the lease was re-dispatched since
            del self.pending[task_id]
            t.num_failure = 0
            self.done.append(t)
            self._maybe_roll_pass()
            self._snapshot()

    def task_failed(self, task_id, epoch):
        """go TaskFailed (:455), epoch-guarded."""
        with self._mu:
            t = self.pending.get(task_id)
            if t is None:
                return
            self._process_failed(t, epoch)

    # -- save-model arbitration (go RequestSaveModel :481) --------------
    def request_save_model(self, trainer_id, block_secs):
        """Return True iff *this* trainer should save the checkpoint.

        Conventionally trainer 0 saves, but any trainer can be
        preempted; the master elects one saver for a ``block_secs``
        window (python/paddle/v2/master/client.py:38-56)."""
        if trainer_id is None or trainer_id == "":
            raise ValueError("trainer id is empty")
        trainer_id = str(trainer_id)
        with self._mu:
            now = self._clock()
            if self._saving_until <= now:
                self._saving_trainer = ""
            need = (self._saving_trainer == "" or
                    self._saving_trainer == trainer_id)
            if need:
                self._saving_trainer = trainer_id
                self._saving_until = now + block_secs
            return need

    # -- observability --------------------------------------------------
    def stats(self):
        with self._mu:
            return {"todo": len(self.todo), "pending": len(self.pending),
                    "done": len(self.done), "failed": len(self.failed),
                    "cur_pass": self.cur_pass}
