"""Reader decorators: composable python-generator transforms.

Parity: reference ``python/paddle/reader/decorator.py`` (map_readers,
shuffle:58, buffered, compose, chain, firstn, xmap_readers:243,
multiprocess_reader:338, cache) — same contract: a *reader creator* is a
zero-arg callable returning an iterator over samples.
"""

import itertools
import queue
import random
import threading

__all__ = [
    "ComposeNotAligned",
    "map_readers",
    "buffered",
    "compose",
    "chain",
    "shuffle",
    "firstn",
    "xmap_readers",
    "multiprocess_reader",
    "cache",
    "bucket_by_length",
    "checkpointable",
    "CheckpointableReader",
    "Fake",
    "PipeReader",
]


class ComposeNotAligned(ValueError):
    pass


def map_readers(func, *readers):
    """func applied across the zip of readers' samples."""
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size):
    """Pool-based shuffle (reference decorator.py:58)."""
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b
    return data_reader


class CheckpointableReader:
    """Position-tracking wrapper over a reader creator — the reader leg
    of exact-resume checkpoints (``TrainState`` captures it alongside
    params/optimizer/PRNG state).

    Tracks ``(epoch, offset)``: how many epochs the source has been
    fully consumed, and how many items of the current epoch were
    yielded.  ``state_dict()``/``load_state_dict()`` round-trip that
    position; the first iteration after a restore FAST-FORWARDS by
    drawing and discarding ``offset`` items from a fresh source
    iterator, so the next item yielded is exactly the one the killed
    run would have trained on.  Exactness requires a deterministic
    source (fixed-seed shuffle, stable file order) — the same property
    the loss-trajectory drill already needs.

    Used as a reader creator: ``reader()`` returns the epoch's
    iterator, like any other decorator product.
    """

    def __init__(self, reader_creator):
        if not callable(reader_creator):
            raise TypeError(
                "checkpointable() wraps a reader CREATOR (zero-arg "
                "callable returning an iterator); got %r"
                % type(reader_creator).__name__)
        self._creator = reader_creator
        self._epoch = 0
        self._offset = 0
        self._skip_debt = 0      # fast-forward remainder; spans epochs

    def state_dict(self):
        return {"epoch": self._epoch, "offset": self._offset}

    def load_state_dict(self, state):
        self._epoch = int(state["epoch"])
        self._offset = int(state["offset"])
        # the restored position is authoritative: pending fast-forward
        # debt from before the restore would skip healthy batches AT
        # the restored position (the rollback protocol re-applies its
        # own fast_forward after the restore)
        self._skip_debt = 0

    def fast_forward(self, n):
        """Advance the position ``n`` items WITHOUT yielding them — the
        guardian's rollback-recovery uses this to jump past a poisoned
        window (quarantined batches that would deterministically re-trip
        the sentinel on replay).  Takes effect at the next iteration(s):
        unlike the saved ``offset`` (whose overshoot of a SHRUNK source
        resets at the epoch boundary), a fast-forward that overshoots
        the epoch carries its remainder into the next epoch — the
        poisoned window must be skipped, however the epochs fall."""
        self._skip_debt += max(0, int(n))
        return self._offset + self._skip_debt

    def __call__(self):
        it = iter(self._creator())
        skip = self._offset
        for _ in range(skip):
            try:
                next(it)
            except StopIteration:
                # source shrank below the saved offset: treat as an
                # epoch boundary rather than replaying a partial epoch
                self._epoch += 1
                self._offset = 0
                return
        while self._skip_debt:
            try:
                next(it)
            except StopIteration:
                # the skip spans the epoch boundary: roll the epoch,
                # keep the remaining debt for the next iterator
                self._epoch += 1
                self._offset = 0
                return
            self._skip_debt -= 1
            self._offset += 1
        for item in it:
            self._offset += 1
            yield item
        self._epoch += 1
        self._offset = 0

    def __iter__(self):
        return self()


def checkpointable(reader):
    """Wrap a reader creator so its position checkpoints and restores
    exactly (see ``CheckpointableReader``)."""
    return CheckpointableReader(reader)


def chain(*readers):
    """Concatenate readers end to end."""
    def reader():
        for r in readers:
            for e in r():
                yield e
    return reader


def compose(*readers, **kwargs):
    """Zip readers into tuple samples; check_alignment validates equal
    lengths (reference ComposeNotAligned)."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum(list(map(make_tuple, outputs)), ())
    return reader


def buffered(reader, size):
    """Background-thread prefetch buffer (reference buffered)."""
    class _End:
        pass

    def data_reader():
        r = reader()
        q = queue.Queue(maxsize=size)

        def fill():
            try:
                for d in r:
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e
    return data_reader


def firstn(reader, n):
    def data_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item
    return data_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel-thread map over a reader (reference xmap_readers:243)."""
    end = object()

    def data_reader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)
        out_order = [0]

        def read_worker():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample) if order else sample)
            in_q.put(end)

        def map_worker():
            sample = in_q.get()
            while sample is not end:
                if order:
                    order_id, data = sample
                    result = mapper(data)
                    while order_id != out_order[0]:
                        threading.Event().wait(0.001)
                    out_q.put(result)
                    out_order[0] += 1
                else:
                    out_q.put(mapper(sample))
                sample = in_q.get()
            in_q.put(end)  # relay for sibling workers
            out_q.put(end)

        t_read = threading.Thread(target=read_worker, daemon=True)
        t_read.start()
        workers = []
        for _ in range(process_num):
            t = threading.Thread(target=map_worker, daemon=True)
            t.start()
            workers.append(t)

        finished = 0
        while finished < process_num:
            sample = out_q.get()
            if sample is end:
                finished += 1
            else:
                yield sample
    return data_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Fork one OS process per reader (reference multiprocess_reader:338).
    Samples interleave in arrival order.  A worker that DIES re-raises in
    the consumer — a crashed shard must never read as a clean (truncated)
    end-of-stream."""
    import multiprocessing as mp
    import pickle
    import traceback

    _ERR = "__mp_reader_worker_error__"

    def queue_reader():
        q = mp.Queue(queue_size)

        def worker(r):
            try:
                for sample in r():
                    q.put(pickle.dumps(sample))
            except BaseException:  # noqa: BLE001 — relayed to the consumer
                q.put((_ERR, traceback.format_exc()))
                return
            q.put(None)

        procs = [mp.Process(target=worker, args=(r,), daemon=True)
                 for r in readers]
        for p in procs:
            p.start()
        finished = 0
        while finished < len(readers):
            item = q.get()
            if item is None:
                finished += 1
            elif isinstance(item, tuple) and item and item[0] == _ERR:
                for p in procs:
                    p.terminate()
                raise RuntimeError(
                    "multiprocess_reader worker failed:\n" + item[1])
            else:
                yield pickle.loads(item)
        for p in procs:
            p.join()
    return queue_reader


def cache(reader):
    """Materialize once, replay from memory.

    An interrupted first pass (early break, ``firstn`` wrapper) must not
    poison the cache, so each uncached pass rebuilds from scratch and only
    a fully-consumed pass is kept.
    """
    all_data = []
    state = {"cached": False}

    def data_reader():
        if not state["cached"]:
            fresh = []
            for item in reader():
                fresh.append(item)
                yield item
            all_data[:] = fresh
            state["cached"] = True
        else:
            for item in all_data:
                yield item
    return data_reader


def bucket_by_length(reader, length_fn, bucket_bounds, batch_size,
                     drop_last=False):
    """Group variable-length samples into length buckets and yield
    ``(bound, samples)`` batches where every sample's length fits the
    bucket's bound.

    The TPU redesign of the reference's length-bucketing machinery
    (``lod_rank_table_op.cc`` + ``lod_tensor_to_array_op.cc`` +
    ``reorder_lod_tensor_by_rank_op.cc``: in-graph rank tables reorder
    LoD batches by length so RNN steps skip padding): under XLA,
    data-dependent in-graph reordering would defeat static shapes, so
    bucketing moves host-side — each bucket pads to its own FIXED bound,
    giving ``len(bucket_bounds)`` jit signatures total while cutting the
    padding waste of pad-to-max batching.  Feed a bucket's batch with
    ``DataFeeder.feed(samples, pad_to=bound)``.

    ``length_fn(sample) -> int``; samples longer than the last bound
    raise (declare a final bound >= the true maximum).  Trailing
    partial batches flush at end-of-stream unless ``drop_last``.

    ``batch_size`` may be a per-bucket list (short buckets take larger
    batches so tokens-per-step — and therefore step efficiency — stays
    roughly constant across buckets, the bucket_by_sequence_length
    recipe).
    """
    raw_bounds = [int(b) for b in bucket_bounds]
    if not raw_bounds:
        raise ValueError("bucket_bounds must be non-empty")
    if isinstance(batch_size, (list, tuple)):
        if len(batch_size) != len(raw_bounds):
            raise ValueError("batch_size list must match bucket_bounds")
        raw_sizes = [int(b) for b in batch_size]
    else:
        raw_sizes = [int(batch_size)] * len(raw_bounds)
    # sizes sort WITH their bounds: callers pair them positionally
    pairs = sorted(zip(raw_bounds, raw_sizes))
    bounds = [b for b, _ in pairs]
    sizes = [s for _, s in pairs]

    def data_reader():
        buckets = [[] for _ in bounds]
        for sample in reader():
            n = int(length_fn(sample))
            for i, b in enumerate(bounds):
                if n <= b:
                    buckets[i].append(sample)
                    if len(buckets[i]) == sizes[i]:
                        yield bounds[i], buckets[i]
                        buckets[i] = []
                    break
            else:
                raise ValueError(
                    "sample length %d exceeds the largest bucket bound %d"
                    % (n, bounds[-1]))
        if not drop_last:
            for b, bucket in zip(bounds, buckets):
                if bucket:
                    yield b, bucket
    return data_reader


class Fake(object):
    """Replay the first epoch's samples forever (reference decorator.py
    Fake — the throughput-testing reader that removes data-source cost
    from the measurement)."""

    def __init__(self):
        self.fake_reader = None

    def __call__(self, reader, length):
        def fake():
            if self.fake_reader is None:
                self.fake_reader = list(
                    item for item, _ in zip(reader(), range(length)))
                if not self.fake_reader:
                    raise ValueError(
                        "Fake: the wrapped reader produced no samples")
            for i in range(length):
                yield self.fake_reader[i % len(self.fake_reader)]

        return fake


class PipeReader(object):
    """Stream records from a shell command's stdout (reference
    decorator.py PipeReader — the HDFS/S3 `hadoop fs -cat`-style
    ingestion path).  ``get_line`` yields decoded lines with a bounded
    read buffer."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        if not isinstance(command, str):
            raise TypeError("a command string is required")
        if file_type not in ("gzip", "plain"):
            raise TypeError("file_type %s is not allowed" % file_type)
        import shlex
        import subprocess
        self.command = command
        self.bufsize = bufsize
        self.file_type = file_type
        self.process = subprocess.Popen(
            shlex.split(command), bufsize=bufsize, stdout=subprocess.PIPE)

    def get_line(self, cut_lines=True, line_break="\n"):
        import codecs
        stream = self.process.stdout
        if self.file_type == "gzip":
            import gzip
            stream = gzip.GzipFile(fileobj=stream)
        # incremental decoder: a multi-byte UTF-8 sequence split across
        # two reads decodes correctly instead of becoming U+FFFD pairs
        decoder = codecs.getincrementaldecoder("utf-8")(errors="replace")
        remained = ""
        while True:
            buf = stream.read(self.bufsize)
            if not buf:
                break
            buf = remained + decoder.decode(buf)
            if not cut_lines:
                remained = ""
                if buf:
                    yield buf
                continue
            lines = buf.split(line_break)
            remained = lines.pop()
            for line in lines:
                yield line
        remained += decoder.decode(b"", final=True)
        if remained:
            yield remained
