"""Reader creators (reference ``python/paddle/reader/creator.py:19``:
np_array, text_file, recordio; plus the ``open_files`` parallel
multi-file reader family from
``operators/reader/open_files_op.cc`` re-designed host-side)."""

import pickle

__all__ = ["np_array", "text_file", "recordio", "open_recordio_files"]


def np_array(x):
    """Yield rows of a numpy array."""
    import numpy as np

    arr = np.asarray(x)

    def reader():
        yield from arr

    return reader


def text_file(path):
    """Yield lines of a text file, newline-stripped."""

    def reader():
        with open(path, "r") as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, buf_size=100):
    """Yield unpickled samples from record files written by
    ``recordio.convert_reader_to_recordio_file``, read ahead through a
    ``buf_size`` buffer thread (reference creator.py:60)."""
    from .. import recordio as rio
    from .decorator import buffered

    raw = rio.reader_creator(paths)

    def reader():
        for rec in raw():
            yield pickle.loads(rec)

    return buffered(reader, buf_size)


def open_recordio_files(paths, num_workers=4, chunks_per_task=1,
                        prefetch=256, unpickle=True, mapper=None,
                        repeat=False):
    """Parallel multi-file recordio reader: the ``open_files_op.cc``
    capability (N files scanned by M threads feeding one queue),
    re-designed host-side with worker PROCESSES (python decode does not
    thread) over CHUNK-RANGE shards.

    Every file is split into ``chunks_per_task``-chunk tasks
    (``recordio.Scanner(skip_chunks, max_chunks)`` — chunk skipping
    never decodes payloads); tasks round-robin across ``num_workers``
    processes whose outputs interleave in arrival order through a
    ``prefetch``-deep queue.  Sample order is therefore nondeterministic
    across workers (exactly like the reference's multi-thread reader);
    use ``num_workers=1`` for deterministic order.

    ``mapper`` (picklable sample -> sample) runs INSIDE the worker
    processes — the decode/augment stage (jpeg decode,
    ``dataset.image.simple_transform``) parallelizes with the scan
    instead of serializing on the consumer.

    ``repeat=True`` makes each worker loop its task list forever (the
    steady-state epoch loop): the worker pool persists instead of
    re-forking per epoch — the consumer takes as many samples as it
    needs and abandons the (daemon) workers when done.
    """
    from .. import recordio as rio
    from .decorator import multiprocess_reader

    if isinstance(paths, str):
        paths = [p for p in paths.split(",") if p]

    tasks = []
    for p in paths:
        n = rio.num_chunks(p)
        for start in range(0, max(n, 1), chunks_per_task):
            tasks.append((p, start, chunks_per_task))

    num_workers = max(1, min(num_workers, len(tasks)))

    def make_worker(worker_tasks):
        def worker_reader():
            while True:
                for path, skip, cnt in worker_tasks:
                    with rio.Scanner(path, skip_chunks=skip,
                                     max_chunks=cnt) as s:
                        for rec in s:
                            sample = pickle.loads(rec) if unpickle \
                                else rec
                            yield mapper(sample) if mapper is not None \
                                else sample
                if not repeat:
                    return
        return worker_reader

    workers = [make_worker(tasks[i::num_workers])
               for i in range(num_workers)]
    if num_workers == 1:
        return workers[0]
    return multiprocess_reader(workers, queue_size=prefetch)
