"""Reader creators (reference ``python/paddle/reader/creator.py:19``:
np_array, text_file, recordio)."""

import pickle

__all__ = ["np_array", "text_file", "recordio"]


def np_array(x):
    """Yield rows of a numpy array."""
    import numpy as np

    arr = np.asarray(x)

    def reader():
        yield from arr

    return reader


def text_file(path):
    """Yield lines of a text file, newline-stripped."""

    def reader():
        with open(path, "r") as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, buf_size=100):
    """Yield unpickled samples from record files written by
    ``recordio.convert_reader_to_recordio_file``, read ahead through a
    ``buf_size`` buffer thread (reference creator.py:60)."""
    from .. import recordio as rio
    from .decorator import buffered

    raw = rio.reader_creator(paths)

    def reader():
        for rec in raw():
            yield pickle.loads(rec)

    return buffered(reader, buf_size)
