"""Reader framework: decorators + device-prefetching PyReader.

Parity: reference ``python/paddle/reader/`` + the py_reader op family
(``operators/reader/create_py_reader_op.cc``,
``create_double_buffer_reader_op.cc``, ``lod_tensor_blocking_queue.h``) —
TPU-native: PyReader is a host thread that stages feed dicts onto the
device ahead of the training loop (double buffering over the host link),
not an in-graph op chain; under jit the executor consumes device-resident
arrays with zero extra copies.
"""

import queue
import threading

from .decorator import *  # noqa: F401,F403
from . import creator  # noqa: F401
from . import decorator  # noqa: F401

__all__ = decorator.__all__ + ["PyReader", "batch"]


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (reference
    python/paddle/v2/minibatch.py / paddle.batch)."""
    def batch_reader():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batch_reader


class PyReader:
    """Host->device prefetch pipeline.

    ``decorate_batch_reader(reader, feeder, place)``: reader yields lists
    of samples; feeder converts them to feed dicts; a daemon thread
    device_puts up to ``capacity`` batches ahead.  Iterate to get
    device-resident feed dicts for Executor.run.
    """

    def __init__(self, capacity=4):
        self.capacity = capacity
        self._reader = None
        self._feeder = None
        self._place = None

    def decorate_batch_reader(self, reader, feeder, place=None):
        self._reader = reader
        self._feeder = feeder
        self._place = place
        return self

    def decorate_paddle_reader(self, reader, feeder, place=None):
        # reference alias
        return self.decorate_batch_reader(reader, feeder, place)

    def __iter__(self):
        import jax

        if self._reader is None:
            raise RuntimeError("call decorate_batch_reader first")
        dev = self._place.jax_device() if self._place is not None else None
        q = queue.Queue(maxsize=self.capacity)
        end = object()
        failure = []   # producer exception, re-raised on the consumer

        def producer():
            try:
                for rows in self._reader():
                    feed = self._feeder.feed(rows)
                    if dev is not None:
                        feed = {
                            k: jax.device_put(v, dev)
                            for k, v in feed.items()
                        }
                    q.put(feed)
            except BaseException as e:  # noqa: BLE001 — must cross threads
                failure.append(e)
            finally:
                q.put(end)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is end:
                if failure:
                    # a swallowed producer error would masquerade as
                    # end-of-data; surface it where the training loop is
                    raise failure[0]
                break
            yield item
