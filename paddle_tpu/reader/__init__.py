"""Reader framework: decorators + device-prefetching pipeline.

Parity: reference ``python/paddle/reader/`` + the py_reader op family
(``operators/reader/create_py_reader_op.cc``,
``create_double_buffer_reader_op.cc``, ``lod_tensor_blocking_queue.h``) —
TPU-native: ``DevicePrefetcher`` is a host thread that converts and
stages feed dicts onto the device ahead of the training loop (double
buffering over the host link generalized to a capacity-N window), not an
in-graph op chain; under jit the executor consumes device-resident
arrays with zero extra copies.  ``PyReader`` is the reference-named
facade over it.
"""

import queue
import threading
import weakref

from .. import monitor
from .decorator import *  # noqa: F401,F403
from . import creator  # noqa: F401
from . import decorator  # noqa: F401

__all__ = decorator.__all__ + ["DevicePrefetcher", "PyReader", "batch"]


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (reference
    python/paddle/v2/minibatch.py / paddle.batch)."""
    def batch_reader():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batch_reader


class DevicePrefetcher:
    """Executor-level device prefetcher: host feed conversion and
    ``jax.device_put`` of step N+1 overlap device compute of step N.

    Generalizes the PyReader double buffer to every feed path:

    * ``source`` — a reader creator (callable returning an iterator) or
      a plain iterable; items are sample-row lists when ``feeder`` is
      given (converted via ``DataFeeder.feed``), feed dicts otherwise.
    * ``place`` — an executor Place (or jax device) for single-device
      staging.
    * ``shardings`` — pjit path: a ``{feed_name: Sharding}`` dict (or one
      Sharding for every feed); arrays arrive on the mesh already laid
      out, so ``ParallelExecutor.run``'s own device_put is a no-op.
    * ``capacity`` — how many staged batches may be in flight ahead of
      the consumer.

    A daemon thread runs the conversion+transfer; iterate to get
    device-resident feed dicts.  A producer exception is re-raised at the
    consumer AFTER already-staged batches drain (the training loop sees
    every good batch, then the real error — not a silent end-of-data).
    ``close()`` (or exiting the context manager) stops the producer and
    joins it even when the consumer abandoned iteration early.  With a
    callable ``source`` or a re-iterable container the prefetcher is
    re-iterable (each epoch spawns a fresh producer over the source);
    over a one-shot iterator a second iteration raises rather than
    silently yielding an empty epoch.
    """

    _END = object()

    def __init__(self, source, feeder=None, place=None, shardings=None,
                 capacity=2):
        self._source = source
        self._feeder = feeder
        self._place = place
        self._shardings = shardings
        self._q = queue.Queue(maxsize=max(1, int(capacity)))
        self._stop = threading.Event()
        self._failure = []
        self._thread = None
        # epoch generation: producer and consumer bind the generation's
        # (queue, stop, failure) at start, so a stale iterator from a
        # superseded epoch can neither steal the new epoch's batches nor
        # kill it when garbage-collected
        self._epoch = 0
        # weakref to the epoch's handed-out consumer generator: detects
        # a live iterator even before its first next() (the producer
        # thread only exists after that), while a dropped-unadvanced
        # iterator reads as dead and doesn't block a fresh one
        self._consumer = None
        # StepStats occupancy + watchdog stall dumps read this
        # prefetcher's queue state through monitor's weak tracking
        monitor.track(self)

    def monitor_state(self):
        return {"kind": "prefetcher", "epoch": self._epoch,
                "occupancy": self._q.qsize(),
                "capacity": self._q.maxsize,
                "stopped": self._stop.is_set()}

    # -- staging -------------------------------------------------------
    def _stage(self, feed):
        import jax

        from ..profiler import RecordEvent

        dev = self._place
        if dev is not None and hasattr(dev, "jax_device"):
            dev = dev.jax_device()
        out = {}
        with RecordEvent("prefetch/h2d_transfer"):
            for k, v in feed.items():
                target = None
                if isinstance(self._shardings, dict):
                    # feeds absent from a partial dict still stage to
                    # the plain device — leaving them on the host would
                    # put their h2d back on the per-step critical path
                    target = self._shardings.get(k, dev)
                elif self._shardings is not None:
                    target = self._shardings
                elif dev is not None:
                    target = dev
                out[k] = jax.device_put(v, target) if target is not None \
                    else v
        return out

    def _producer(self, q, stop, failure):
        try:
            it = self._source() if callable(self._source) \
                else iter(self._source)
            for item in it:
                if stop.is_set():
                    return
                # per-item liveness signal: a producer wedged inside a
                # slow source or device_put shows a stale heartbeat in
                # the watchdog dump, distinct from "queue full, waiting"
                monitor.heartbeat("prefetch/producer")
                feed = self._feeder.feed(item) if self._feeder is not None \
                    else item
                feed = self._stage(feed)
                while not stop.is_set():
                    try:
                        q.put(feed, timeout=0.05)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # noqa: BLE001 — must cross threads
            failure.append(e)
        finally:
            while not stop.is_set():
                try:
                    q.put(self._END, timeout=0.05)
                    break
                except queue.Full:
                    continue

    def _ensure_started(self, epoch, q, stop, failure):
        # called from inside the consumer generator with ITS epoch's
        # objects: a superseded generator (stop set) or one whose epoch
        # was reset before first advance must not spawn a producer for
        # the current epoch's thread slot
        if epoch == self._epoch and self._thread is None \
                and not stop.is_set():
            # named so chrome-trace thread_name metadata and watchdog
            # dumps identify the prefetch worker, not a bare tid
            self._thread = threading.Thread(
                target=self._producer, args=(q, stop, failure),
                name="prefetch-producer-%d" % epoch, daemon=True)
            self._thread.start()

    def _restartable(self):
        """Whether the source can produce a fresh stream per epoch:
        reader creators (callables) and re-iterable containers (lists,
        datasets) can; a one-shot iterator (`iter(x) is x`) cannot."""
        src = self._source
        return callable(src) or iter(src) is not src

    # -- consumer protocol ---------------------------------------------
    def __iter__(self):
        live_consumer = (self._consumer is not None
                         and self._consumer() is not None)
        if live_consumer and not self._stop.is_set():
            if self._restartable():
                # iter() over a live stream from a re-startable source
                # means "fresh epoch from the top" (the documented
                # contract): stop the current producer before
                # restarting, so the new epoch never shares a
                # half-consumed stream
                self.close()
            else:
                # a second live consumer over a one-shot iterator would
                # share the queue, and dropping either would close the
                # epoch under the other — the silent truncation this
                # class exists to prevent
                raise RuntimeError(
                    "DevicePrefetcher already has an active iterator;"
                    " a one-shot iterator source supports a single pass")
        if self._stop.is_set():
            # a finished/closed prefetcher: re-iterable iff the source
            # can produce a fresh stream (reader creators, containers;
            # the PyReader multi-epoch contract).  A one-shot iterator
            # is exhausted — raising beats silently yielding an empty
            # epoch.
            if not self._restartable():
                raise RuntimeError(
                    "DevicePrefetcher over a one-shot iterator is"
                    " exhausted; pass a callable reader creator or a"
                    " re-iterable container to re-iterate")
            self._epoch += 1
            self._q = queue.Queue(maxsize=self._q.maxsize)
            self._stop = threading.Event()
            self._failure = []
            self._thread = None
        gen = self._consume(self._epoch, self._q, self._stop,
                            self._failure)
        self._consumer = weakref.ref(gen)
        return gen

    def _consume(self, epoch, q, stop, failure):
        # q/stop/failure are this epoch's objects, bound at iter() time:
        # a stale generator or producer from a superseded epoch can
        # neither steal the new epoch's batches nor poison it with a
        # stale exception
        try:
            # lazy producer start INSIDE the generator body: a created-
            # but-never-advanced iterator has no thread to leak (its
            # finally below would never run)
            self._ensure_started(epoch, q, stop, failure)
            while True:
                if stop.is_set():
                    return
                try:
                    # bounded wait so a concurrent close() can't strand
                    # the consumer on an empty queue whose producer
                    # already died
                    item = q.get(timeout=0.05)
                except queue.Empty:
                    continue
                if item is self._END:
                    if failure:
                        # a swallowed producer error would masquerade as
                        # end-of-data; surface it where the training
                        # loop is
                        raise failure[0]
                    return
                yield item
        finally:
            # covers GeneratorExit too: an abandoned iteration (early
            # break with the facade dropping this handle) must stop the
            # producer thread, not leave it spinning on a full queue
            # holding staged device batches alive.  Guarded by epoch so
            # a superseded iterator's GC cannot kill the live one.
            if epoch == self._epoch:
                self.close()
            else:
                stop.set()

    def close(self):
        """Stop the producer and join it (idempotent).  Safe mid-stream:
        drains the queue so a blocked ``put`` observes the stop flag."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=10)
        return self

    def __enter__(self):
        # deliberately lazy: starting the producer here would stage
        # batches that __iter__'s fresh-epoch restart (callable sources)
        # then discards — the first iter() starts it
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class PyReader:
    """Host->device prefetch pipeline (reference-named facade over
    ``DevicePrefetcher``).

    ``decorate_batch_reader(reader, feeder, place)``: reader yields lists
    of samples; feeder converts them to feed dicts; a daemon thread
    device_puts up to ``capacity`` batches ahead.  Iterate to get
    device-resident feed dicts for Executor.run.
    """

    def __init__(self, capacity=4):
        self.capacity = capacity
        self._reader = None
        self._feeder = None
        self._place = None

    def decorate_batch_reader(self, reader, feeder, place=None):
        self._reader = reader
        self._feeder = feeder
        self._place = place
        return self

    def decorate_paddle_reader(self, reader, feeder, place=None):
        # reference alias
        return self.decorate_batch_reader(reader, feeder, place)

    def __iter__(self):
        if self._reader is None:
            raise RuntimeError("call decorate_batch_reader first")
        # a fresh prefetcher per iteration: PyReader is re-iterable
        return iter(DevicePrefetcher(
            self._reader, feeder=self._feeder, place=self._place,
            capacity=self.capacity))
