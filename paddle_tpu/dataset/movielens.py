"""MovieLens-1M reader creators (reference
``python/paddle/dataset/movielens.py``: ml-1m.zip with
movies.dat/users.dat/ratings.dat '::'-separated tables; samples are
user features + movie features + normalized rating; deterministic
90/10 train/test split)."""

import random
import re
import zipfile

import numpy as np

from . import common

__all__ = ["train", "test", "get_movie_title_dict", "max_movie_id",
           "max_user_id", "max_job_id", "age_table", "movie_categories",
           "user_info", "movie_info", "MovieInfo", "UserInfo"]

URL = "http://files.grouplens.org/datasets/movielens/ml-1m.zip"
MD5 = "c4d9eecfca2ab87c1945afe126590906"

age_table = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, title_dict):
        return [self.index,
                [categories_dict[c] for c in self.categories],
                [title_dict[w.lower()] for w in self.title.split()]]

    def __repr__(self):
        return "<MovieInfo id(%d), title(%s), categories(%s)>" % (
            self.index, self.title, self.categories)


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age,
                self.job_id]

    def __repr__(self):
        return "<UserInfo id(%d), gender(%s), age(%d), job(%d)>" % (
            self.index, "M" if self.is_male else "F",
            age_table[self.age], self.job_id)


class _Meta:
    """Parsed tables + vocabularies (reference __initialize_meta_info__)."""

    def __init__(self, zip_path):
        pattern = re.compile(r"^(\d+)::(.*)::(.*)$")
        self.movies = {}
        self.users = {}
        self.ratings = []
        categories = set()
        title_words = set()
        with zipfile.ZipFile(zip_path) as z:
            base = z.namelist()[0].split("/")[0]
            with z.open("%s/movies.dat" % base) as f:
                for line in f:
                    m = pattern.match(line.decode("latin-1").strip())
                    if not m:
                        continue
                    idx, title, cats = m.groups()
                    cats = cats.split("|")
                    categories.update(cats)
                    title = re.sub(r"\(\d{4}\)$", "", title).strip()
                    title_words.update(w.lower() for w in title.split())
                    self.movies[int(idx)] = MovieInfo(idx, cats, title)
            with z.open("%s/users.dat" % base) as f:
                for line in f:
                    parts = line.decode("latin-1").strip().split("::")
                    if len(parts) < 4:
                        continue
                    uid, gender, age, job = parts[:4]
                    self.users[int(uid)] = UserInfo(uid, gender, age, job)
            with z.open("%s/ratings.dat" % base) as f:
                for line in f:
                    parts = line.decode("latin-1").strip().split("::")
                    if len(parts) < 4:
                        continue
                    uid, mid, rating = int(parts[0]), int(parts[1]), \
                        float(parts[2])
                    if uid in self.users and mid in self.movies:
                        self.ratings.append((uid, mid, rating))
        self.categories_dict = {c: i for i, c in
                                enumerate(sorted(categories))}
        self.title_dict = {w: i for i, w in enumerate(sorted(title_words))}

    def sample(self, uid, mid, rating):
        # rating normalized to [-3, 5]: r*2-5 (reference movielens.py:163)
        return (self.users[uid].value() +
                self.movies[mid].value(self.categories_dict,
                                       self.title_dict) +
                [[rating * 2 - 5.0]])


_meta_cache = {}


def _meta():
    if "m" not in _meta_cache:
        _meta_cache["m"] = _Meta(common.download(URL, "movielens", MD5))
    return _meta_cache["m"]


def _reader(is_test, test_ratio=0.1, rand_seed=0):
    def reader():
        meta = _meta()
        rng = random.Random(rand_seed)
        for uid, mid, rating in meta.ratings:
            if (rng.random() < test_ratio) == is_test:
                yield meta.sample(uid, mid, rating)

    return reader


def train():
    return _reader(is_test=False)


def test():
    return _reader(is_test=True)


def get_movie_title_dict():
    return _meta().title_dict


def movie_categories():
    return _meta().categories_dict


def max_movie_id():
    return max(_meta().movies)


def max_user_id():
    return max(_meta().users)


def max_job_id():
    return max(u.job_id for u in _meta().users.values())


def movie_info():
    return _meta().movies


def user_info():
    return _meta().users
