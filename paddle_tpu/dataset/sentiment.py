"""NLTK movie-reviews sentiment reader creators (reference
``python/paddle/dataset/sentiment.py``: 2000 labeled reviews, word-freq
vocabulary, 1600/400 train/test split; samples are (word ids, 0/1)).

The corpus loader is separated from the sample pipeline so the pipeline
is testable with injected documents (the reference hard-wires nltk;
nltk may be absent in this image — ``train``/``test`` raise a clear
ImportError in that case)."""

__all__ = ["train", "test", "get_word_dict", "build_samples",
           "NUM_TRAINING_INSTANCES", "NUM_TOTAL_INSTANCES"]

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000


def _load_corpus():
    try:
        import nltk
        from nltk.corpus import movie_reviews
    except ImportError as e:  # pragma: no cover - env-dependent
        raise ImportError(
            "paddle_tpu.dataset.sentiment needs nltk's movie_reviews "
            "corpus; install nltk and run "
            "nltk.download('movie_reviews')") from e
    docs = [(list(movie_reviews.words(fid)), cat)
            for cat in movie_reviews.categories()
            for fid in movie_reviews.fileids(cat)]
    return docs


def build_word_dict(documents):
    """Frequency-sorted word -> id (reference get_word_dict)."""
    freq = {}
    for words, _ in documents:
        for w in words:
            w = w.lower()
            freq[w] = freq.get(w, 0) + 1
    ranked = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
    return {w: i for i, (w, _) in enumerate(ranked)}


def build_samples(documents, word_dict=None, shuffle_seed=0):
    """(word ids, label) pairs, deterministically shuffled; label 0 =
    negative, 1 = positive (reference sorted_label convention)."""
    import random

    word_dict = word_dict or build_word_dict(documents)
    cats = sorted({c for _, c in documents})
    label_of = {c: i for i, c in enumerate(cats)}
    samples = [([word_dict[w.lower()] for w in words], label_of[cat])
               for words, cat in documents]
    random.Random(shuffle_seed).shuffle(samples)
    return samples


_cache = {}


def _samples():
    if "s" not in _cache:
        docs = _load_corpus()
        _cache["d"] = build_word_dict(docs)
        _cache["s"] = build_samples(docs, _cache["d"])
    return _cache["s"]


def get_word_dict():
    _samples()
    return _cache["d"]


def train():
    def reader():
        yield from _samples()[:NUM_TRAINING_INSTANCES]

    return reader


def test():
    def reader():
        yield from _samples()[NUM_TRAINING_INSTANCES:]

    return reader
