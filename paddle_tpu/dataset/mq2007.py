"""MQ2007 learning-to-rank dataset (LETOR 4.0, TREC 2007 Million Query).

Reader creators over the LETOR text format
(``<relevance> qid:<id> 1:<v> 2:<v> ... # comment``), yielding
point-wise, pair-wise, or list-wise samples per query.

Parity: reference ``python/paddle/dataset/mq2007.py`` (same public
surface: Query/QueryList, gen_plain_txt/gen_point/gen_pair/gen_list,
query_filter, load_from_text, train/test creators).  The parser and
generators are original; the archive is a .rar, and this environment has
no rar extractor, so ``fetch`` downloads the archive and extraction is
the caller's (documented) responsibility unless the extracted tree
already exists.
"""

import functools
import os

import numpy as np

from . import common

__all__ = ["train", "test", "fetch", "load_from_text", "query_filter",
           "gen_plain_txt", "gen_point", "gen_pair", "gen_list",
           "Query", "QueryList"]

URL = ("http://www.bigdatalab.ac.cn/benchmark/upload/download_source/"
       "7b6dbbe2-842c-11e4-a536-bcaec51b9163_MQ2007.rar")
MD5 = "7be1640ae95c6408dab0ae7207bdc706"

FEATURE_DIM = 46


class Query(object):
    """One query-document pair: relevance score, query id, dense feature
    vector, and the trailing comment of its LETOR line."""

    def __init__(self, query_id=-1, relevance_score=-1, feature_vector=None,
                 description=""):
        self.query_id = query_id
        self.relevance_score = relevance_score
        self.feature_vector = feature_vector if feature_vector is not None \
            else []
        self.description = description

    def __str__(self):
        feats = " ".join(
            "%d:%s" % (i + 1, v)
            for i, v in enumerate(self.feature_vector))
        s = "%s qid:%d %s" % (self.relevance_score, self.query_id, feats)
        if self.description:
            s += " #" + self.description   # keep the line re-parseable
        return s

    @staticmethod
    def parse(line, fill_missing=-1):
        """Parse one LETOR line; returns a Query or None on a malformed
        line.  Missing feature slots are filled with ``fill_missing``."""
        line = line.strip()
        if not line:
            return None
        body, _, comment = line.partition("#")
        parts = body.split()
        if len(parts) < 2 or not parts[1].startswith("qid:"):
            return None
        try:
            rel = int(parts[0])
            qid = int(parts[1][len("qid:"):])
        except ValueError:
            return None
        feats = {}
        for tok in parts[2:]:
            idx, _, val = tok.partition(":")
            try:
                feats[int(idx)] = float(val)
            except ValueError:
                return None
        # fixed-width FEATURE_DIM vectors (LETOR 4.0 has 46 features):
        # lines that omit trailing features still yield uniform-length
        # vectors so gen_list/gen_pair can stack documents within a
        # query; an out-of-range index means the line is not MQ2007
        if feats and max(feats) > FEATURE_DIM:
            return None
        vec = [feats.get(i + 1, fill_missing) for i in range(FEATURE_DIM)]
        return Query(query_id=qid, relevance_score=rel, feature_vector=vec,
                     description=comment.strip())

    # reference-API spelling
    def _parse_(self, line, fill_missing=-1):
        return Query.parse(line, fill_missing)


class QueryList(object):
    """All documents of one query, ordered by relevance for the
    list-wise generators."""

    def __init__(self, querylist=None):
        self.querylist = list(querylist) if querylist else []
        self.query_id = self.querylist[0].query_id if self.querylist else -1

    def __iter__(self):
        return iter(self.querylist)

    def __len__(self):
        return len(self.querylist)

    def __getitem__(self, i):
        return self.querylist[i]

    def _add_query(self, query):
        if self.query_id == -1:
            self.query_id = query.query_id
        elif query.query_id != self.query_id:
            raise ValueError(
                "query id %d does not match list id %d"
                % (query.query_id, self.query_id))
        self.querylist.append(query)

    def _correct_ranking_(self):
        self.querylist.sort(key=lambda q: q.relevance_score, reverse=True)


def gen_plain_txt(querylist):
    """Yield (query_id, relevance, feature_vector) per document."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    querylist._correct_ranking_()
    for query in querylist:
        yield querylist.query_id, query.relevance_score, \
            np.array(query.feature_vector)


def gen_point(querylist):
    """Point-wise samples: (relevance, feature_vector)."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    querylist._correct_ranking_()
    for query in querylist:
        yield query.relevance_score, np.array(query.feature_vector)


def gen_pair(querylist, partial_order="full"):
    """Pair-wise samples: (label=[1], higher_doc, lower_doc) over all
    C(n,2) pairs with differing relevance ("full") or only adjacent
    ranks ("neighbour")."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    querylist._correct_ranking_()
    n = len(querylist)
    pairs = ((i, j) for i in range(n) for j in range(i + 1, n)) \
        if partial_order == "full" else \
        ((i, i + 1) for i in range(n - 1))
    for i, j in pairs:
        left, right = querylist[i], querylist[j]
        if left.relevance_score == right.relevance_score:
            continue
        hi, lo = (left, right) \
            if left.relevance_score > right.relevance_score else (right, left)
        yield np.array([1]), np.array(hi.feature_vector), \
            np.array(lo.feature_vector)


def gen_list(querylist):
    """List-wise sample: (relevance column, feature matrix) per query."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    querylist._correct_ranking_()
    labels = np.array([[q.relevance_score] for q in querylist])
    feats = np.array([q.feature_vector for q in querylist])
    yield labels, feats


def query_filter(querylists):
    """Drop queries whose documents are all relevance 0 (no ranking
    signal)."""
    return [ql for ql in querylists
            if sum(q.relevance_score for q in ql) != 0]


def load_from_text(filepath, shuffle=False, fill_missing=-1, data_dir=None):
    """Parse a LETOR file into a list of QueryList.  ``filepath`` may be
    absolute or relative to ``data_dir`` (default: the extracted MQ2007
    tree next to the downloaded archive)."""
    if not os.path.isabs(filepath):
        base = data_dir if data_dir is not None else _data_home()
        filepath = os.path.join(base, filepath)
    querylists = []
    current = None
    with open(filepath) as f:
        for line in f:
            q = Query.parse(line, fill_missing)
            if q is None:
                continue
            if current is None or q.query_id != current.query_id:
                if current is not None:
                    querylists.append(current)
                current = QueryList()
            current._add_query(q)
    if current is not None:
        querylists.append(current)
    if shuffle:
        np.random.shuffle(querylists)
    return querylists


def _data_home():
    return os.path.dirname(fetch())


def __reader__(filepath, format="pairwise", shuffle=False, fill_missing=-1):
    querylists = query_filter(
        load_from_text(filepath, shuffle=shuffle, fill_missing=fill_missing))
    for querylist in querylists:
        if format == "plain_txt":
            yield next(gen_plain_txt(querylist))
        elif format == "pointwise":
            yield next(gen_point(querylist))
        elif format == "pairwise":
            for pair in gen_pair(querylist):
                yield pair
        elif format == "listwise":
            yield next(gen_list(querylist))
        else:
            raise ValueError("unknown format %r" % format)


train = functools.partial(__reader__,
                          filepath="MQ2007/MQ2007/Fold1/train.txt")
test = functools.partial(__reader__, filepath="MQ2007/MQ2007/Fold1/test.txt")


def fetch():
    """Download the MQ2007 archive; returns its path.  The archive is a
    .rar — this environment ships no rar extractor, so if the extracted
    ``MQ2007/`` tree is not already present next to the archive the
    caller must unrar it (``unrar x MQ2007.rar``) before using the
    readers."""
    return common.download(URL, "MQ2007", MD5)
