"""PTB language-model reader creators (reference
``python/paddle/dataset/imikolov.py``: n-gram and seq modes over the
tarball's train/valid splits)."""

import collections
import tarfile

from . import common

__all__ = ["train", "test", "build_dict"]

URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz"
MD5 = "30177ea32e27c525793142b6bf2c8e2d"


class DataType:
    NGRAM = 1
    SEQ = 2


def word_count(f, word_freq=None):
    if word_freq is None:
        word_freq = collections.defaultdict(int)
    for line in f:
        words = line.decode().strip().split()
        for w in words:
            word_freq[w] += 1
        word_freq["<s>"] += 1
        word_freq["<e>"] += 1
    return word_freq


def build_dict(min_word_freq=50):
    path = common.download(URL, "imikolov", MD5)
    with tarfile.open(path) as tf:
        train_f = tf.extractfile("./simple-examples/data/ptb.train.txt")
        word_freq = word_count(train_f)
        word_freq.pop("<unk>", None)
        word_freq = [x for x in word_freq.items() if x[1] > min_word_freq]
        dictionary = sorted(word_freq, key=lambda x: (-x[1], x[0]))
        words, _ = list(zip(*dictionary))
        word_idx = dict(list(zip(words, range(len(words)))))
        word_idx["<unk>"] = len(words)
    return word_idx


def reader_creator(filename, word_idx, n, data_type):
    def reader():
        path = common.download(URL, "imikolov", MD5)
        with tarfile.open(path) as tf:
            f = tf.extractfile(filename)
            unk = word_idx["<unk>"]
            for line in f:
                if DataType.NGRAM == data_type:
                    assert n > -1, "n must be set for ngram mode"
                    line = ["<s>"] + line.decode().strip().split() + ["<e>"]
                    if len(line) >= n:
                        line = [word_idx.get(w, unk) for w in line]
                        for i in range(n, len(line) + 1):
                            yield tuple(line[i - n:i])
                elif DataType.SEQ == data_type:
                    line = line.decode().strip().split()
                    ids = [word_idx.get(w, unk) for w in line]
                    src_seq = [word_idx["<s>"]] + ids
                    trg_seq = ids + [word_idx["<e>"]]
                    if n > 0 and len(ids) > n:
                        continue
                    yield src_seq, trg_seq
                else:
                    raise ValueError("unknown data type")
    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return reader_creator("./simple-examples/data/ptb.train.txt", word_idx,
                          n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return reader_creator("./simple-examples/data/ptb.valid.txt", word_idx,
                          n, data_type)
