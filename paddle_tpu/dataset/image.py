"""Image decode/augment helpers for the dataset readers.

The input-pipeline preprocessing vocabulary of the reference
(``python/paddle/dataset/image.py``: batch_images_from_tar,
load_image/load_image_bytes, resize_short, center/random crop, flip,
to_chw, simple_transform, load_and_transform) — original
implementation.  Decoding uses cv2 when importable with a numpy/PIL
fallback; the geometric transforms are pure numpy so the host-side
pipeline (reader/decorator.py workers) has no hard native dependency.

All functions take/return HWC uint8-or-float numpy arrays (color images
BGR like the reference's cv2 convention) except ``to_chw``.
"""

import os
import pickle
import tarfile

import numpy as np

try:
    import cv2
except ImportError:  # pragma: no cover - cv2 present in this image
    cv2 = None

__all__ = [
    "batch_images_from_tar", "load_image_bytes", "load_image",
    "resize_short", "to_chw", "center_crop", "random_crop",
    "left_right_flip", "simple_transform", "load_and_transform",
]


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Pack raw image bytes + labels from a tar into pickled batch files;
    returns the meta file listing the batch paths (resumable: an existing
    output directory short-circuits)."""
    batch_dir = data_file + "_batch"
    out_path = os.path.join(batch_dir, dataset_name)
    meta_file = os.path.join(batch_dir, dataset_name + ".txt")
    # the meta file is written LAST, so its existence is the completion
    # marker; a run interrupted mid-pack leaves out_path without it and
    # repacking resumes cleanly (overwriting the partial batches)
    if os.path.exists(meta_file):
        return meta_file
    os.makedirs(out_path, exist_ok=True)

    data, labels, file_id = [], [], 0

    def flush():
        nonlocal file_id, data, labels
        if not data:
            return
        with open(os.path.join(out_path, "batch_%d" % file_id), "wb") as f:
            pickle.dump({"label": labels, "data": data}, f, protocol=2)
        file_id += 1
        data, labels = [], []

    with tarfile.open(data_file) as tf:
        for mem in tf.getmembers():
            if mem.name not in img2label:
                continue
            data.append(tf.extractfile(mem).read())
            labels.append(img2label[mem.name])
            if len(data) == num_per_batch:
                flush()
    flush()
    with open(meta_file, "w") as meta:
        for fn in sorted(os.listdir(out_path)):
            meta.write(os.path.abspath(os.path.join(out_path, fn)) + "\n")
    return meta_file


def load_image_bytes(bytes, is_color=True):  # noqa: A002 - reference name
    """Decode an encoded image buffer to an HWC (or HW) uint8 array."""
    if cv2 is not None:
        flag = cv2.IMREAD_COLOR if is_color else cv2.IMREAD_GRAYSCALE
        buf = np.frombuffer(bytes, dtype=np.uint8)
        return cv2.imdecode(buf, flag)
    import io

    from PIL import Image

    im = Image.open(io.BytesIO(bytes))
    im = im.convert("RGB" if is_color else "L")
    arr = np.asarray(im)
    return arr[:, :, ::-1] if is_color else arr  # match cv2's BGR


def load_image(file, is_color=True):  # noqa: A002 - reference name
    """Load an image file to an HWC (or HW) uint8 array."""
    with open(file, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def _resize(im, h, w):
    if cv2 is not None:
        return cv2.resize(im, (w, h), interpolation=cv2.INTER_LANCZOS4)
    # numpy bilinear fallback
    ih, iw = im.shape[:2]
    ys = (np.arange(h) + 0.5) * ih / h - 0.5
    xs = (np.arange(w) + 0.5) * iw / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, ih - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, iw - 1)
    y1 = np.minimum(y0 + 1, ih - 1)
    x1 = np.minimum(x0 + 1, iw - 1)
    wy = (ys - y0).clip(0, 1)
    wx = (xs - x0).clip(0, 1)
    imf = im.astype(np.float32)
    if im.ndim == 2:
        top = imf[y0][:, x0] * (1 - wx) + imf[y0][:, x1] * wx
        bot = imf[y1][:, x0] * (1 - wx) + imf[y1][:, x1] * wx
    else:
        wx = wx[:, None]
        top = imf[y0][:, x0] * (1 - wx) + imf[y0][:, x1] * wx
        bot = imf[y1][:, x0] * (1 - wx) + imf[y1][:, x1] * wx
    wy = wy[:, None] if im.ndim == 2 else wy[:, None, None]
    out = top * (1 - wy) + bot * wy
    return out.astype(im.dtype)


def resize_short(im, size):
    """Resize so the shorter edge becomes ``size`` (aspect preserved)."""
    h, w = im.shape[:2]
    if h > w:
        h, w = int(round(size * h / w)), size
    else:
        h, w = size, int(round(size * w / h))
    return _resize(im, h, w)


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (the layout the NCHW feed path expects)."""
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    y = (h - size) // 2
    x = (w - size) // 2
    return im[y:y + size, x:x + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    y = np.random.randint(0, h - size + 1)
    x = np.random.randint(0, w - size + 1)
    return im[y:y + size, x:x + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize-short -> (random crop + coin-flip mirror | center crop) ->
    CHW float32, optionally mean-subtracted (per-channel or
    elementwise)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype("float32")
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and is_color:
            mean = mean[:, np.newaxis, np.newaxis]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
