"""IMDB sentiment reader creators (reference
``python/paddle/dataset/imdb.py``: aclImdb tar parsing, word-freq dict,
(ids, 0/1) samples)."""

import re
import string
import tarfile

from . import common

__all__ = ["train", "test", "word_dict"]

URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"
MD5 = "7c2ac02c03563afcf9b574c7e56c153a"


def tokenize(pattern):
    path = common.download(URL, "imdb", MD5)
    with tarfile.open(path) as tarf:
        tf = tarf.next()
        while tf is not None:
            if bool(pattern.match(tf.name)):
                data = tarf.extractfile(tf).read().decode("latin-1")
                yield data.lower().translate(
                    str.maketrans("", "", string.punctuation)).split()
            tf = tarf.next()


def build_dict(pattern, cutoff):
    word_freq = {}
    for doc in tokenize(pattern):
        for word in doc:
            word_freq[word] = word_freq.get(word, 0) + 1
    word_freq = [x for x in word_freq.items() if x[1] > cutoff]
    dictionary = sorted(word_freq, key=lambda x: (-x[1], x[0]))
    words, _ = list(zip(*dictionary))
    word_idx = dict(list(zip(words, range(len(words)))))
    word_idx["<unk>"] = len(words)
    return word_idx


def reader_creator(pos_pattern, neg_pattern, word_idx):
    unk = word_idx["<unk>"]

    def reader():
        for doc in tokenize(pos_pattern):
            yield [word_idx.get(w, unk) for w in doc], 0
        for doc in tokenize(neg_pattern):
            yield [word_idx.get(w, unk) for w in doc], 1
    return reader


def train(word_idx):
    return reader_creator(
        re.compile(r"aclImdb/train/pos/.*\.txt$"),
        re.compile(r"aclImdb/train/neg/.*\.txt$"), word_idx)


def test(word_idx):
    return reader_creator(
        re.compile(r"aclImdb/test/pos/.*\.txt$"),
        re.compile(r"aclImdb/test/neg/.*\.txt$"), word_idx)


def word_dict(cutoff=150):
    return build_dict(
        re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"),
        cutoff)
