"""UCI housing reader creators (reference
``python/paddle/dataset/uci_housing.py``: whitespace table, feature
normalization over the train split, 80/20 train/test split)."""

import numpy as np

from . import common

__all__ = ["train", "test", "feature_range"]

URL = "http://paddlemodels.bj.bcebos.com/uci_housing/housing.data"
MD5 = "d4accdce7a25600298819f8e28e8d593"
FEATURE_NUM = 14
TRAIN_RATIO = 0.8

_cache = {}


def _load():
    if "data" in _cache:
        return _cache["data"]
    path = common.download(URL, "uci_housing", MD5)
    data = np.loadtxt(path).reshape(-1, FEATURE_NUM)
    maxs = data.max(axis=0)
    mins = data.min(axis=0)
    avgs = data.mean(axis=0)
    split = int(data.shape[0] * TRAIN_RATIO)
    for i in range(FEATURE_NUM - 1):
        data[:, i] = (data[:, i] - avgs[i]) / (maxs[i] - mins[i])
    _cache["data"] = (data, split)
    return _cache["data"]


def feature_range(maximums, minimums):
    pass  # plotting helper in the reference; intentionally a no-op


def train():
    def reader():
        data, split = _load()
        for row in data[:split]:
            yield row[:-1].astype("float32"), \
                np.array(row[-1:], "float32")
    return reader


def test():
    def reader():
        data, split = _load()
        for row in data[split:]:
            yield row[:-1].astype("float32"), \
                np.array(row[-1:], "float32")
    return reader
