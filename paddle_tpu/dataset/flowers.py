"""Oxford-102 flowers reader creators (reference
``python/paddle/dataset/flowers.py``: jpeg tarball + imagelabels.mat +
setid.mat; samples are (float32 CHW image in [0,1], label int in
[0,101]))."""

import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "valid", "reader_creator"]

DATA_URL = ("http://paddlemodels.bj.bcebos.com/flowers/102flowers.tgz")
DATA_MD5 = "52808999861908f626f3c1f4e79d11fa"
LABEL_URL = ("http://paddlemodels.bj.bcebos.com/flowers/imagelabels.mat")
LABEL_MD5 = "e0620be6f572b9609742df49c70aed4d"
SETID_URL = ("http://paddlemodels.bj.bcebos.com/flowers/setid.mat")
SETID_MD5 = "a5357ecc9cb78c4bef273ce3793fc85c"

# NOTE: deliberately swapped, matching the reference (flowers.py:59-60):
# the 6149-image 'tstid' split is used for TRAINING and the 1020-image
# 'trnid' split for testing
TRAIN_FLAG = "tstid"
TEST_FLAG = "trnid"
VALID_FLAG = "valid"


def _load_image(blob, resize=96):
    """jpeg bytes -> float32 CHW in [0,1], center-cropped square then
    resized (reference simple_transform capability via PIL)."""
    import io

    from PIL import Image

    img = Image.open(io.BytesIO(blob)).convert("RGB")
    w, h = img.size
    s = min(w, h)
    img = img.crop(((w - s) // 2, (h - s) // 2,
                    (w + s) // 2, (h + s) // 2))
    img = img.resize((resize, resize))
    arr = np.asarray(img, dtype="float32") / 255.0
    return arr.transpose(2, 0, 1)


def reader_creator(data_file, label_file, setid_file, flag, resize=96,
                   sample_limit=None):
    """Iterate the split's image ids from setid.mat, read jpegs from the
    tar, labels (1..102 -> 0..101) from imagelabels.mat."""
    import scipy.io

    def reader():
        ids = scipy.io.loadmat(setid_file)[flag].ravel()
        labels = scipy.io.loadmat(label_file)["labels"].ravel()
        with tarfile.open(data_file) as tf:
            members = {m.name: m for m in tf.getmembers()}
            count = 0
            for image_id in ids:
                name = "jpg/image_%05d.jpg" % image_id
                if name not in members:
                    continue
                blob = tf.extractfile(members[name]).read()
                yield (_load_image(blob, resize),
                       int(labels[image_id - 1]) - 1)
                count += 1
                if sample_limit and count >= sample_limit:
                    return

    return reader


def _files():
    return (common.download(DATA_URL, "flowers", DATA_MD5),
            common.download(LABEL_URL, "flowers", LABEL_MD5),
            common.download(SETID_URL, "flowers", SETID_MD5))


def train(resize=96):
    data, label, setid = _files()
    return reader_creator(data, label, setid, TRAIN_FLAG, resize)


def test(resize=96):
    data, label, setid = _files()
    return reader_creator(data, label, setid, TEST_FLAG, resize)


def valid(resize=96):
    data, label, setid = _files()
    return reader_creator(data, label, setid, VALID_FLAG, resize)
