"""Synthetic dataset creators for offline benchmarking/testing (no
reference analog; the reference benchmark's --use_fake_data flag covers
the same need, benchmark/fluid/args.py)."""

import numpy as np

__all__ = ["images", "sequences", "regression"]


def images(n=1024, shape=(3, 32, 32), classes=10, seed=0):
    def reader():
        rng = np.random.RandomState(seed)
        proj = rng.rand(int(np.prod(shape)))
        for _ in range(n):
            x = rng.rand(*shape).astype("float32")
            y = int(x.reshape(-1) @ proj * classes /
                    proj.sum()) % classes
            yield x, y
    return reader


def sequences(n=1024, vocab=100, max_len=20, classes=2, seed=0):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            ln = rng.randint(1, max_len + 1)
            seq = rng.randint(0, vocab, (ln,)).astype("int64")
            y = int(seq.mean() > vocab / 2)
            yield seq, y
    return reader


def regression(n=1024, dim=13, seed=0):
    def reader():
        rng = np.random.RandomState(seed)
        w = rng.rand(dim)
        for _ in range(n):
            x = rng.rand(dim).astype("float32")
            y = np.float32(x @ w + 0.1 * rng.randn())
            yield x, np.array([y], "float32")
    return reader
