"""CoNLL-2005 semantic-role-labeling reader (reference
``python/paddle/dataset/conll05.py``: gzipped words/props column files
inside a tarball; prop bracket tags expand to B-/I-/O sequences; samples
are the 8 SRL feature sequences + label ids)."""

import gzip
import tarfile

from . import common

__all__ = ["test", "get_dict", "get_embedding", "corpus_reader",
           "reader_creator"]

DATA_URL = ("http://paddlemodels.bj.bcebos.com/conll05st/"
            "conll05st-tests.tar.gz")
DATA_MD5 = "387719152ae52d60422c016e92a742fc"
WORDDICT_URL = "http://paddlemodels.bj.bcebos.com/conll05st/wordDict.txt"
WORDDICT_MD5 = "ea7fb7d4c75cc6254716f0177a506baa"
VERBDICT_URL = "http://paddlemodels.bj.bcebos.com/conll05st/verbDict.txt"
VERBDICT_MD5 = "0d2977293bbb6cbefab5b0f97db1e77c"
TRGDICT_URL = "http://paddlemodels.bj.bcebos.com/conll05st/targetDict.txt"
TRGDICT_MD5 = "d8c7f03ceb5fc2e5a0fa7503a4353751"
EMB_URL = "http://paddlemodels.bj.bcebos.com/conll05st/emb"
EMB_MD5 = "bf436eb0faa1f6f9103017f8be57cdb7"

UNK_IDX = 0


def load_label_dict(filename):
    """Expand the label list: B-x/I-x for starred tags, O (reference
    load_label_dict, conll05.py:48)."""
    d = {}
    tag_dict = set()
    with open(filename) as f:
        for line in f:
            line = line.strip()
            if line.startswith("B-"):
                tag_dict.add(line[2:])
            elif line.startswith("I-"):
                tag_dict.add(line[2:])
    index = 0
    for tag in sorted(tag_dict):
        d["B-" + tag] = index
        index += 1
        d["I-" + tag] = index
        index += 1
    d["O"] = index
    return d


def load_dict(filename):
    d = {}
    with open(filename) as f:
        for i, line in enumerate(f):
            d[line.strip()] = i
    return d


def _expand_bracket_labels(lbl):
    """One predicate's prop column -> B-/I-/O tag sequence (reference
    corpus_reader's bracket state machine, conll05.py:110-133)."""
    out = []
    cur_tag = "O"
    in_bracket = False
    for token in lbl:
        if token == "*" and not in_bracket:
            out.append("O")
        elif token == "*" and in_bracket:
            out.append("I-" + cur_tag)
        elif token == "*)":
            out.append("I-" + cur_tag)
            in_bracket = False
        elif "(" in token and ")" in token:
            cur_tag = token[1:token.find("*")]
            out.append("B-" + cur_tag)
            in_bracket = False
        elif "(" in token:
            cur_tag = token[1:token.find("*")]
            out.append("B-" + cur_tag)
            in_bracket = True
        else:
            raise RuntimeError("unexpected label token %r" % token)
    return out


def corpus_reader(data_path, words_name, props_name):
    """Yield (sentence words, verb, B/I/O tag sequence) per predicate."""

    def reader():
        with tarfile.open(data_path) as tf, \
                gzip.GzipFile(fileobj=tf.extractfile(words_name)) as wf, \
                gzip.GzipFile(fileobj=tf.extractfile(props_name)) as pf:
            sentence = []
            prop_cols = []
            for wline, pline in zip(wf, pf):
                word = wline.decode("utf-8").strip()
                props = pline.decode("utf-8").strip().split()
                if not props:  # sentence boundary
                    if prop_cols:
                        n_cols = len(prop_cols[0])
                        cols = [[row[i] for row in prop_cols]
                                for i in range(n_cols)]
                        verbs = [v for v in cols[0] if v != "-"]
                        for i, lbl in enumerate(cols[1:]):
                            yield (sentence, verbs[i],
                                   _expand_bracket_labels(lbl))
                    sentence = []
                    prop_cols = []
                else:
                    sentence.append(word)
                    prop_cols.append(props)

    return reader


def reader_creator(corpus_rdr, word_dict=None, verb_dict=None,
                   label_dict=None):
    """Map corpus samples to the 8 SRL input sequences + label ids
    (reference reader_creator, conll05.py:150): word, ctx_n2/n1/0/p1/p2,
    verb, mark, label."""
    w = word_dict or {}
    v = verb_dict or {}
    lbl = label_dict or {}

    def reader():
        for sentence, predicate, labels in corpus_rdr():
            sen_len = len(sentence)
            verb_index = labels.index("B-V")
            mark = [0] * sen_len
            # context window around the predicate
            if verb_index > 0:
                mark[verb_index - 1] = 1
            mark[verb_index] = 1
            if verb_index < sen_len - 1:
                mark[verb_index + 1] = 1

            ctx_n2 = sentence[verb_index - 2] if verb_index > 1 else "bos"
            ctx_n1 = sentence[verb_index - 1] if verb_index > 0 else "bos"
            ctx_0 = sentence[verb_index]
            ctx_p1 = sentence[verb_index + 1] \
                if verb_index < sen_len - 1 else "eos"
            ctx_p2 = sentence[verb_index + 2] \
                if verb_index < sen_len - 2 else "eos"

            word_idx = [w.get(x, UNK_IDX) for x in sentence]
            pred_idx = [v.get(predicate, UNK_IDX)] * sen_len
            label_idx = [lbl[x] for x in labels]
            yield (word_idx,
                   [w.get(ctx_n2, UNK_IDX)] * sen_len,
                   [w.get(ctx_n1, UNK_IDX)] * sen_len,
                   [w.get(ctx_0, UNK_IDX)] * sen_len,
                   [w.get(ctx_p1, UNK_IDX)] * sen_len,
                   [w.get(ctx_p2, UNK_IDX)] * sen_len,
                   pred_idx, mark, label_idx)

    return reader


def get_dict():
    word_dict = load_dict(
        common.download(WORDDICT_URL, "conll05st", WORDDICT_MD5))
    verb_dict = load_dict(
        common.download(VERBDICT_URL, "conll05st", VERBDICT_MD5))
    label_dict = load_label_dict(
        common.download(TRGDICT_URL, "conll05st", TRGDICT_MD5))
    return word_dict, verb_dict, label_dict


def get_embedding():
    return common.download(EMB_URL, "conll05st", EMB_MD5)


def test():
    word_dict, verb_dict, label_dict = get_dict()
    data = common.download(DATA_URL, "conll05st", DATA_MD5)
    words = "conll05st-release/test.wsj/words/test.wsj.words.gz"
    props = "conll05st-release/test.wsj/props/test.wsj.props.gz"
    return reader_creator(corpus_reader(data, words, props),
                          word_dict, verb_dict, label_dict)
