"""MNIST reader creators (reference ``python/paddle/dataset/mnist.py``:
idx-format parsing, train/test creators yielding (image[784] in [-1,1],
label))."""

import gzip
import struct

import numpy as np

from . import common

__all__ = ["train", "test"]

URL_PREFIX = "http://yann.lecun.com/exdb/mnist/"
TRAIN_IMAGE_MD5 = "f68b3c2dcbeaaa9fbdd348bbdeb94873"
TRAIN_LABEL_MD5 = "d53e105ee54ea40749a09fcbcd1e9432"
TEST_IMAGE_MD5 = "9fb629c4189551a2d022fa330f9573f3"
TEST_LABEL_MD5 = "ec29112dd5afa0611ce80d1b7f02629c"


def reader_creator(image_filename, label_filename, buffer_size=100):
    def reader():
        with gzip.open(image_filename, "rb") as imgf, \
                gzip.open(label_filename, "rb") as lblf:
            magic, n, rows, cols = struct.unpack(">IIII", imgf.read(16))
            magic_l, n_l = struct.unpack(">II", lblf.read(8))
            assert n == n_l
            per = rows * cols
            for _ in range(0, n, buffer_size):
                count = min(buffer_size, n)
                imgs = np.frombuffer(
                    imgf.read(count * per), dtype="uint8"
                ).reshape(-1, per)
                if imgs.shape[0] == 0:
                    break
                labels = np.frombuffer(lblf.read(imgs.shape[0]),
                                       dtype="uint8")
                imgs = imgs.astype("float32") / 255.0 * 2.0 - 1.0
                for im, lb in zip(imgs, labels):
                    yield im, int(lb)
    return reader


def train():
    return reader_creator(
        common.download(URL_PREFIX + "train-images-idx3-ubyte.gz", "mnist",
                        TRAIN_IMAGE_MD5),
        common.download(URL_PREFIX + "train-labels-idx1-ubyte.gz", "mnist",
                        TRAIN_LABEL_MD5))


def test():
    return reader_creator(
        common.download(URL_PREFIX + "t10k-images-idx3-ubyte.gz", "mnist",
                        TEST_IMAGE_MD5),
        common.download(URL_PREFIX + "t10k-labels-idx1-ubyte.gz", "mnist",
                        TEST_LABEL_MD5))
