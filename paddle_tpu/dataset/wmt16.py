"""WMT16 en<->de reader creators (reference
``python/paddle/dataset/wmt16.py``: BPE-processed tarball with a
``wmt16/{train,test,val}`` member of tab-separated pairs; dictionaries
are built from the training split on first use and cached; samples are
(src_ids, trg_ids, trg_ids_next))."""

import os
import tarfile
from collections import defaultdict

from . import common

__all__ = ["train", "test", "validation", "get_dict", "reader_creator",
           "fetch"]

URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz"
MD5 = "0c38af81d9e3a6f689eba04fbf1a47ba"

TOTAL_EN_WORDS = 11250
TOTAL_DE_WORDS = 19220

START_MARK = "<s>"
END_MARK = "<e>"
UNK_MARK = "<unk>"


def _build_dict(tar_path, dict_size, lang):
    freq = defaultdict(int)
    with tarfile.open(tar_path) as tf:
        for line in tf.extractfile("wmt16/train"):
            parts = line.decode("utf-8").strip().split("\t")
            if len(parts) != 2:
                continue
            sen = parts[0] if lang == "en" else parts[1]
            for w in sen.split():
                freq[w] += 1
    words = [w for w, _ in
             sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))]
    vocab = [START_MARK, END_MARK, UNK_MARK] + words[:dict_size - 3]
    return {w: i for i, w in enumerate(vocab)}


def _dict_cache_path(dict_size, lang):
    return os.path.join(common.DATA_HOME, "wmt16",
                        "%s_%d.dict" % (lang, dict_size))


def _load_dict(tar_path, dict_size, lang, reverse=False):
    path = _dict_cache_path(dict_size, lang)
    if not os.path.exists(path):
        d = _build_dict(tar_path, dict_size, lang)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            for w, _ in sorted(d.items(), key=lambda kv: kv[1]):
                f.write(w + "\n")
    d = {}
    with open(path) as f:
        for i, line in enumerate(f):
            d[line.rstrip("\n")] = i
    if reverse:
        d = {v: k for k, v in d.items()}
    return d


def _clip_sizes(src_dict_size, trg_dict_size, src_lang):
    src_total = TOTAL_EN_WORDS if src_lang == "en" else TOTAL_DE_WORDS
    trg_total = TOTAL_DE_WORDS if src_lang == "en" else TOTAL_EN_WORDS
    return min(src_dict_size, src_total), min(trg_dict_size, trg_total)


def reader_creator(tar_path, file_name, src_dict_size, trg_dict_size,
                   src_lang):
    def reader():
        src_dict = _load_dict(tar_path, src_dict_size, src_lang)
        trg_dict = _load_dict(tar_path, trg_dict_size,
                              "de" if src_lang == "en" else "en")
        start, end, unk = (src_dict[START_MARK], src_dict[END_MARK],
                           src_dict[UNK_MARK])
        src_col = 0 if src_lang == "en" else 1
        with tarfile.open(tar_path) as tf:
            for line in tf.extractfile(file_name):
                parts = line.decode("utf-8").strip().split("\t")
                if len(parts) != 2:
                    continue
                src_ids = [start] + [src_dict.get(w, unk)
                                     for w in parts[src_col].split()] \
                    + [end]
                trg_words = parts[1 - src_col].split()
                trg_ids = [trg_dict.get(w, unk) for w in trg_words]
                yield (src_ids, [start] + trg_ids, trg_ids + [end])

    return reader


def _tar():
    return common.download(URL, "wmt16", MD5, save_name="wmt16.tar.gz")


def train(src_dict_size, trg_dict_size, src_lang="en"):
    s, t = _clip_sizes(src_dict_size, trg_dict_size, src_lang)
    return reader_creator(_tar(), "wmt16/train", s, t, src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    s, t = _clip_sizes(src_dict_size, trg_dict_size, src_lang)
    return reader_creator(_tar(), "wmt16/test", s, t, src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    s, t = _clip_sizes(src_dict_size, trg_dict_size, src_lang)
    return reader_creator(_tar(), "wmt16/val", s, t, src_lang)


def get_dict(lang, dict_size, reverse=False):
    total = TOTAL_EN_WORDS if lang == "en" else TOTAL_DE_WORDS
    return _load_dict(_tar(), min(dict_size, total), lang, reverse)


def fetch():
    _tar()
