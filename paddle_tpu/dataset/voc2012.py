"""VOC2012 segmentation reader creators (reference
``python/paddle/dataset/voc2012.py``: tarball with ImageSets lists,
JPEGImages and SegmentationClass PNGs; samples are (HWC uint8 image,
HW uint8 label mask))."""

import io
import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "val", "reader_creator"]

VOC_URL = ("http://host.robots.ox.ac.uk/pascal/VOC/voc2012/"
           "VOCtrainval_11-May-2012.tar")
VOC_MD5 = "6cd6e144f989b92b3379bac3b3de84fd"

SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"


def reader_creator(filename, sub_name):
    from PIL import Image

    def reader():
        with tarfile.open(filename) as tf:
            members = {m.name: m for m in tf.getmembers()}
            set_member = members[SET_FILE.format(sub_name)]
            for line in tf.extractfile(set_member):
                name = line.decode("utf-8").strip()
                if not name:
                    continue
                img_blob = tf.extractfile(
                    members[DATA_FILE.format(name)]).read()
                lbl_blob = tf.extractfile(
                    members[LABEL_FILE.format(name)]).read()
                img = np.asarray(Image.open(io.BytesIO(img_blob))
                                 .convert("RGB"), dtype="uint8")
                lbl = np.asarray(Image.open(io.BytesIO(lbl_blob)),
                                 dtype="uint8")
                yield img, lbl

    return reader


def _tar():
    return common.download(VOC_URL, "voc2012", VOC_MD5)


def train():
    return reader_creator(_tar(), "trainval")


def test():
    return reader_creator(_tar(), "train")


def val():
    return reader_creator(_tar(), "val")
