"""Dataset download/cache machinery (reference
``python/paddle/dataset/common.py``: DATA_HOME, download with md5 check,
cached unpacking).  In egress-restricted environments place files in
``$PADDLE_TPU_DATA_HOME`` (default ``~/.cache/paddle_tpu/dataset``)
manually; ``download`` verifies and reuses them."""

import hashlib
import os
import shutil

__all__ = ["DATA_HOME", "download", "md5file"]

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA_HOME",
    os.path.expanduser("~/.cache/paddle_tpu/dataset"))


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)
    return path


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    dirname = must_mkdirs(os.path.join(DATA_HOME, module_name))
    filename = os.path.join(
        dirname, save_name if save_name else url.split("/")[-1])
    if os.path.exists(filename) and (md5sum is None or
                                     md5file(filename) == md5sum):
        return filename
    try:
        import urllib.request
        tmp = filename + ".part"
        urllib.request.urlretrieve(url, tmp)
        if md5sum is not None and md5file(tmp) != md5sum:
            os.remove(tmp)
            raise IOError("md5 mismatch downloading %s" % url)
        shutil.move(tmp, filename)
        return filename
    except Exception as e:
        raise IOError(
            "cannot download %s (%s). In offline environments place the "
            "file at %s manually." % (url, e, filename))
