"""WMT14 en->fr reader creators (reference
``python/paddle/dataset/wmt14.py``: tarball of tab-separated parallel
text + src.dict/trg.dict files; samples are (src_ids, trg_ids,
trg_ids_next) with <s>/<e>/<unk> conventions and the >80-token filter).
"""

import tarfile

from . import common

__all__ = ["train", "test", "get_dict", "reader_creator"]

URL_TRAIN = ("http://paddlemodels.bj.bcebos.com/wmt/wmt14.tgz")
MD5_TRAIN = "0791583d57d5beb693b9414c5b36798c"

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2
MAX_LEN = 80


def _dicts_from_tar(tar_path, dict_size):
    """First ``dict_size`` lines of the *.src.dict / *.trg.dict members;
    line number = word id."""
    out = {}
    with tarfile.open(tar_path) as tf:
        for kind in ("src", "trg"):
            names = [n for n in tf.getnames()
                     if n.endswith("%s.dict" % kind)]
            assert len(names) == 1, names
            d = {}
            for i, line in enumerate(tf.extractfile(names[0])):
                if i >= dict_size:
                    break
                d[line.decode("utf-8").strip()] = i
            out[kind] = d
    return out["src"], out["trg"]


def reader_creator(tar_path, file_name, dict_size):
    def reader():
        src_dict, trg_dict = _dicts_from_tar(tar_path, dict_size)
        with tarfile.open(tar_path) as tf:
            names = [n for n in tf.getnames() if n.endswith(file_name)]
            for name in names:
                for line in tf.extractfile(name):
                    parts = line.decode("utf-8").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_words = [START] + parts[0].split() + [END]
                    src_ids = [src_dict.get(w, UNK_IDX)
                               for w in src_words]
                    trg_words = parts[1].split()
                    trg_ids = [trg_dict.get(w, UNK_IDX)
                               for w in trg_words]
                    if len(src_ids) > MAX_LEN or len(trg_ids) > MAX_LEN:
                        continue
                    trg_ids_next = trg_ids + [trg_dict[END]]
                    trg_ids = [trg_dict[START]] + trg_ids
                    yield src_ids, trg_ids, trg_ids_next

    return reader


def _tar():
    return common.download(URL_TRAIN, "wmt14", MD5_TRAIN)


def train(dict_size):
    return reader_creator(_tar(), "train/train", dict_size)


def test(dict_size):
    return reader_creator(_tar(), "test/test", dict_size)


def get_dict(dict_size, reverse=True):
    """(src_dict, trg_dict); reversed (id->word) by default, matching
    the reference's decode-time usage."""
    src, trg = _dicts_from_tar(_tar(), dict_size)
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg
