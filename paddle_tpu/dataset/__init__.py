"""Dataset package (reference ``python/paddle/dataset/``: mnist, cifar,
imdb, uci_housing, imikolov, movielens, wmt14/16, conll05, flowers,
sentiment, voc2012 with download+cache).  Loaders parse the standard
archives from the cache dir (common.DATA_HOME); ``synthetic`` provides
offline generators."""

from . import common, mnist, cifar, imdb, uci_housing, imikolov  # noqa: F401
from . import conll05, movielens, wmt14, wmt16  # noqa: F401
from . import flowers, sentiment, voc2012  # noqa: F401
from . import image, mq2007  # noqa: F401
from . import synthetic  # noqa: F401

__all__ = ["common", "mnist", "cifar", "imdb", "uci_housing", "imikolov",
           "conll05", "movielens", "wmt14", "wmt16", "flowers",
           "sentiment", "voc2012", "image", "mq2007", "synthetic"]
