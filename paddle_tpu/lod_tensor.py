"""LoD tensor construction helpers (reference
python/paddle/fluid/lod_tensor.py:23,92).

TPU-native redesign: there is no LoDTensor runtime type — variable-
length sequences are padded ``[batch, time, ...]`` arrays plus an
``@LEN`` companion vector (see layers/io.py data).  ``create_lod_tensor``
therefore returns a ``PaddedSequence`` view holding exactly those two
arrays, and ``as_feed(name)`` yields the feed-dict entries executors
expect.  One nesting level is supported: the padded+@LEN design
flattens the reference's recursive LoD by construction (SURVEY §5
long-context ruling); deeper nesting raises with that citation.
"""

import numpy as np

__all__ = ["PaddedSequence", "LoDTensor", "LoDTensorArray",
           "create_lod_tensor", "create_random_int_lodtensor"]


class PaddedSequence(object):
    """What create_lod_tensor returns: the padded batch + lengths."""

    def __init__(self, data, seq_lens):
        self.data = data
        self.seq_lens = np.asarray(seq_lens, dtype="int32")

    def recursive_sequence_lengths(self):
        """Length-based LoD, reference LoDTensor API."""
        return [list(int(l) for l in self.seq_lens)]

    def has_valid_recursive_sequence_lengths(self):
        return bool(np.all(self.seq_lens >= 0) and
                    self.data.shape[1] >= int(self.seq_lens.max(initial=0)))

    def shape(self):
        return tuple(self.data.shape)

    def as_feed(self, name):
        """Feed-dict entries for a data var declared with lod_level=1."""
        return {name: self.data, name + "@LEN": self.seq_lens}

    def __array__(self, dtype=None):
        a = self.data
        return a.astype(dtype) if dtype is not None else a


class LoDTensor(PaddedSequence):
    """Constructible host LoD tensor (reference pybind ``core.LoDTensor``
    surface: ``set`` / ``set_recursive_sequence_lengths`` / ``lod``).
    The storage is the padded+@LEN pair; offset-based ``lod()`` is
    derived from the lengths on demand."""

    def __init__(self, data=None, seq_lens=None):
        if data is None:
            data = np.zeros((0, 0), dtype="float32")
        if seq_lens is None:
            seq_lens = np.zeros((0,), dtype="int32")
        super().__init__(np.asarray(data), seq_lens)

    def set(self, array, place=None):
        """Stage a host array (``place`` accepted for parity; residency
        is decided at feed time by the executor)."""
        self.data = np.asarray(array)

    def set_recursive_sequence_lengths(self, recursive_seq_lens):
        self.seq_lens = np.asarray(_check_lod(recursive_seq_lens),
                                   dtype="int32")

    def lod(self):
        """Offset-based LoD (the reference's native form): one level of
        [0, l0, l0+l1, ...]."""
        if self.seq_lens.size == 0:
            return []
        return [[0] + [int(v) for v in np.cumsum(self.seq_lens)]]

    def set_lod(self, lod):
        if not lod:
            self.seq_lens = np.zeros((0,), dtype="int32")
            return
        if len(lod) > 1:
            raise NotImplementedError(
                "multi-level LoD is flattened by the padded+@LEN design "
                "(SURVEY §5); pass one level of offsets")
        offs = list(lod[0])
        if offs and (offs[0] != 0 or
                     any(b < a for a, b in zip(offs, offs[1:]))):
            raise ValueError(
                "lod offsets must start at 0 and be non-decreasing, "
                "got %s" % (offs,))
        self.seq_lens = np.asarray(
            [b - a for a, b in zip(offs, offs[1:])], dtype="int32")


class LoDTensorArray(list):
    """Host-side tensor array (reference ``core.LoDTensorArray``): a
    plain list of LoDTensor/arrays — in-program arrays are preallocated
    device tensors (layers.create_array), this is the feed/fetch shim."""


def _check_lod(recursive_seq_lens):
    if (not isinstance(recursive_seq_lens, (list, tuple)) or
            len(recursive_seq_lens) == 0):
        raise ValueError("recursive_seq_lens must be a non-empty list of "
                         "lists, e.g. [[2, 3]]")
    if len(recursive_seq_lens) > 1:
        raise NotImplementedError(
            "multi-level LoD is flattened by the padded+@LEN design "
            "(SURVEY §5); pass one level of lengths")
    return [int(l) for l in recursive_seq_lens[0]]


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """Build a PaddedSequence from flat data + lengths (reference
    lod_tensor.py:23).

    ``data`` may be a list of per-sequence lists (word ids -> int64
    [n, 1] as the reference does), a flat numpy array of shape
    [sum(lens), ...], or an existing PaddedSequence (re-checked).
    ``place`` is accepted for parity; arrays stay host-side until fed.
    """
    from .data_feeder import _SequenceConverter

    lens = _check_lod(recursive_seq_lens)
    if isinstance(data, PaddedSequence):
        return create_lod_tensor(
            _unpad(data), recursive_seq_lens, place)
    if isinstance(data, list):
        got = [len(seq) for seq in data]
        if got != lens:
            raise AssertionError(
                "data and recursive_seq_lens do not match: %s vs %s"
                % (got, lens))
        # word-id lists -> int64 [n, 1], as the reference specializes
        conv = _SequenceConverter(shape=(-1, -1, 1), dtype="int64")
        for seq in data:
            conv.feed(np.asarray(seq, dtype="int64").reshape(-1))
        padded, got_lens = conv.done()
        return LoDTensor(padded, got_lens)
    data = np.asarray(data)
    if data.shape[0] != sum(lens):
        raise AssertionError(
            "data rows (%d) != sum of sequence lengths (%d)"
            % (data.shape[0], sum(lens)))
    # split the flat rows per sequence and reuse the DataFeeder padder
    conv = _SequenceConverter(shape=None, dtype=data.dtype)
    off = 0
    for l in lens:
        conv.feed(data[off:off + l])
        off += l
    padded, got_lens = conv.done()
    return LoDTensor(padded, got_lens)


def _unpad(ps):
    rows = []
    for i, l in enumerate(ps.seq_lens):
        rows.append(ps.data[i, :int(l)])
    return np.concatenate(rows, axis=0) if rows else \
        np.zeros((0,) + ps.data.shape[2:], ps.data.dtype)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1):
    """Random-integer sequence batch (reference lod_tensor.py:92): one
    int64 row of shape ``base_shape`` per timestep, lengths as given."""
    lens = _check_lod(recursive_seq_lens)
    total = sum(lens)
    shape = (total,) + tuple(base_shape)
    data = np.random.randint(low, high + 1, size=shape).astype("int64")
    return create_lod_tensor(data, recursive_seq_lens, place)
