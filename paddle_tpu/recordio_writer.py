"""fluid.recordio_writer API parity
(reference ``python/paddle/fluid/recordio_writer.py``): thin re-export
over the native record-file codec in ``paddle_tpu.recordio``."""

from .recordio import Writer, convert_reader_to_recordio_file  # noqa: F401

__all__ = ["Writer", "convert_reader_to_recordio_file"]
