"""DataFeeder: convert python/numpy minibatches into executor feed dicts.

Parity: reference ``python/paddle/fluid/data_feeder.py:83`` (DataFeeder:
converts reader rows into LoDTensors per place; feed_parallel splits across
devices) — TPU-native: produces numpy arrays (the executor moves them to
device); ragged sequence rows are packed/padded via the sequence utilities
instead of LoD.
"""

import numpy as np

from .core import convert_dtype
from .framework import Variable, default_main_program

__all__ = ["DataFeeder"]


class _Converter:
    def __init__(self, shape, dtype):
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.rows = []

    def feed(self, item):
        self.rows.append(np.asarray(item, dtype=self.dtype))

    def done(self):
        arr = np.stack(self.rows) if self.rows else np.zeros((0,), self.dtype)
        if self.shape is not None and -1 not in self.shape[1:]:
            want = tuple(s for s in self.shape if s != -1)
            if arr.size and arr.shape[1:] != want[-len(arr.shape[1:]):]:
                try:
                    arr = arr.reshape((arr.shape[0],) + tuple(
                        s for s in self.shape[1:]))
                except ValueError:
                    pass
        return arr


class _SequenceConverter:
    """Ragged rows -> padded [batch, T, ...] + int32 [batch] lengths (the
    LoD replacement; ``pad_to`` fixes T for static-shape friendliness —
    per-batch max otherwise, which recompiles per distinct T)."""

    def __init__(self, shape, dtype, pad_to=None):
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.pad_to = pad_to
        self.rows = []

    def feed(self, item):
        arr = np.asarray(item, dtype=self.dtype)
        # scalar-per-step shape [D]=[1] declared: accept [T] and lift to [T,1]
        if self.shape is not None:
            trailing = tuple(s for s in self.shape[2:])
            if trailing == (1,) and arr.ndim == 1:
                arr = arr[:, None]
        self.rows.append(arr)

    def done(self):
        lens = np.asarray([r.shape[0] for r in self.rows], dtype=np.int32)
        t = int(self.pad_to) if self.pad_to else int(lens.max() if len(lens)
                                                     else 0)
        if len(self.rows) and any(r.shape[0] > t for r in self.rows):
            raise ValueError(
                "sequence longer than pad_to=%d" % t)
        trailing = self.rows[0].shape[1:] if self.rows else ()
        out = np.zeros((len(self.rows), t) + trailing, self.dtype)
        for i, r in enumerate(self.rows):
            out[i, :r.shape[0]] = r
        return out, lens


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None, pad_to=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_levels = []
        self.place = place
        self.pad_to = pad_to
        if program is None:
            program = default_main_program()
        for v in feed_list:
            if isinstance(v, str):
                v = program.global_block().var(v)
            assert isinstance(v, Variable)
            self.feed_names.append(v.name)
            self.feed_dtypes.append(v.dtype)
            self.feed_shapes.append(v.shape)
            self.feed_lod_levels.append(v.lod_level or 0)

    def feed(self, iterable, pad_to=None):
        """rows of tuples -> {name: batched ndarray}; sequence fields
        (lod_level>=1) additionally produce the '<name>@LEN' array.
        ``pad_to`` overrides the constructor's pad length for this batch
        — the per-bucket pad bound of ``reader.bucket_by_length``."""
        pad = pad_to if pad_to is not None else self.pad_to
        converters = [
            _SequenceConverter(shape, dtype, pad_to=pad)
            if lod >= 1 else _Converter(shape, dtype)
            for shape, dtype, lod in zip(
                self.feed_shapes, self.feed_dtypes, self.feed_lod_levels)
        ]
        for each_sample in iterable:
            assert len(each_sample) == len(converters), (
                "sample has %d fields, expected %d"
                % (len(each_sample), len(converters))
            )
            for item, conv in zip(each_sample, converters):
                conv.feed(item)
        out = {}
        for name, conv, lod in zip(self.feed_names, converters,
                                   self.feed_lod_levels):
            if lod >= 1:
                arr, lens = conv.done()
                out[name] = arr
                out[name + "@LEN"] = lens
            else:
                out[name] = conv.done()
        return out

    def prefetch(self, reader, capacity=2, place=None, shardings=None):
        """Overlapped input pipeline: a ``DevicePrefetcher`` that runs
        this feeder's row->array conversion AND the host->device transfer
        of step N+1 under compute of step N.  ``reader`` yields sample
        rows (a reader creator or iterable); ``shardings`` routes feeds
        onto a pjit mesh (``{name: Sharding}`` or one Sharding for all)
        so ParallelExecutor consumes them with zero extra copies."""
        from .reader import DevicePrefetcher

        if place is None:
            place = self.place
        if place is None and (shardings is None
                              or isinstance(shardings, dict)):
            # no place anywhere would stage nothing (host arrays pass
            # through, h2d lands back on the critical path): default to
            # the accelerator like layers.double_buffer (TPUPlace falls
            # back to the first local device on CPU-only hosts).  A
            # partial shardings dict still needs it for unlisted feeds.
            from .executor import TPUPlace

            place = TPUPlace(0)
        return DevicePrefetcher(
            reader, feeder=self, place=place,
            shardings=shardings, capacity=capacity)

    def feed_parallel(self, iterable, num_places=None):
        """Split one batch into per-device feeds (reference
        data_feeder.py:feed_parallel) — used by the mesh runtime for
        manual per-device feeding; pjit sharding usually replaces this."""
        import math

        rows = list(iterable)
        n = num_places or 1
        per = math.ceil(len(rows) / n)
        old_pad = self.pad_to
        try:
            if old_pad is None and any(l >= 1 for l in self.feed_lod_levels):
                # pad every slice to the global max so the per-device dicts
                # concatenate/stack consistently
                global_max = 0
                for row in rows:
                    for item, lod in zip(row, self.feed_lod_levels):
                        if lod >= 1:
                            global_max = max(global_max,
                                             np.asarray(item).shape[0])
                self.pad_to = global_max or None
            return [self.feed(rows[i * per:(i + 1) * per]) for i in range(n)]
        finally:
            self.pad_to = old_pad
