"""DataFeeder: convert python/numpy minibatches into executor feed dicts.

Parity: reference ``python/paddle/fluid/data_feeder.py:83`` (DataFeeder:
converts reader rows into LoDTensors per place; feed_parallel splits across
devices) — TPU-native: produces numpy arrays (the executor moves them to
device); ragged sequence rows are packed/padded via the sequence utilities
instead of LoD.
"""

import numpy as np

from .core import convert_dtype
from .framework import Variable, default_main_program

__all__ = ["DataFeeder"]


class _Converter:
    def __init__(self, shape, dtype):
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.rows = []

    def feed(self, item):
        self.rows.append(np.asarray(item, dtype=self.dtype))

    def done(self):
        arr = np.stack(self.rows) if self.rows else np.zeros((0,), self.dtype)
        if self.shape is not None and -1 not in self.shape[1:]:
            want = tuple(s for s in self.shape if s != -1)
            if arr.size and arr.shape[1:] != want[-len(arr.shape[1:]):]:
                try:
                    arr = arr.reshape((arr.shape[0],) + tuple(
                        s for s in self.shape[1:]))
                except ValueError:
                    pass
        return arr


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.place = place
        if program is None:
            program = default_main_program()
        for v in feed_list:
            if isinstance(v, str):
                v = program.global_block().var(v)
            assert isinstance(v, Variable)
            self.feed_names.append(v.name)
            self.feed_dtypes.append(v.dtype)
            self.feed_shapes.append(v.shape)

    def feed(self, iterable):
        """rows of tuples -> {name: batched ndarray}."""
        converters = [
            _Converter(shape, dtype)
            for shape, dtype in zip(self.feed_shapes, self.feed_dtypes)
        ]
        for each_sample in iterable:
            assert len(each_sample) == len(converters), (
                "sample has %d fields, expected %d"
                % (len(each_sample), len(converters))
            )
            for item, conv in zip(each_sample, converters):
                conv.feed(item)
        return {
            name: conv.done()
            for name, conv in zip(self.feed_names, converters)
        }

    def feed_parallel(self, iterable, num_places=None):
        """Split one batch into per-device feeds (reference
        data_feeder.py:feed_parallel) — used by the mesh runtime for
        manual per-device feeding; pjit sharding usually replaces this."""
        import math

        rows = list(iterable)
        n = num_places or 1
        per = math.ceil(len(rows) / n)
        return [self.feed(rows[i * per:(i + 1) * per]) for i in range(n)]
