"""Global runtime flags — the gflags/env-whitelist analog.

Parity: the reference defines C++ gflags next to each subsystem
(``FLAGS_check_nan_inf`` in ``framework/operator.cc:31``,
``FLAGS_benchmark`` in ``framework/executor.cc:396``,
``FLAGS_cpu_deterministic``) and re-exports an env-settable whitelist at
import time (``python/paddle/fluid/__init__.py:112-126`` →
``core.init_gflags``).  Here flags are a typed registry: each flag has a
declared type and default, is overridable from the environment at import
(``FLAGS_<name>=...``) and at runtime via ``set_flags``/``get_flags``.

TPU-native semantics of the debugging flags:

* ``check_nan_inf`` — after every executor step, block on the step's
  outputs and verify finiteness of all floating fetches and written-back
  state; raise naming the first offending variable.  (The reference
  checks every op's outputs inside the interpreter loop,
  ``operator.cc:717``; under whole-program jit the step boundary is the
  observable granularity.)
* ``debug_nans`` — op-level localization: enables ``jax_debug_nans``,
  which re-runs a nan-producing jitted step op-by-op to point at the
  guilty primitive.  Finer-grained but globally intrusive; separate
  from ``check_nan_inf`` so the cheap step-level check doesn't flip
  global jax config.
* ``cpu_deterministic`` — forces deterministic XLA reductions
  (``--xla_cpu_enable_fast_math=false`` analog) via jax config.
* ``benchmark`` — per-step wall-clock logging in the executors.
"""

import os
import threading

__all__ = ["set_flags", "get_flags", "register_flag"]

_mu = threading.Lock()
_FLAGS = {}
_TYPES = {}


def register_flag(name, default, typ=None, on_set=None):
    """Declare a flag.  Env var ``FLAGS_<name>`` overrides the default
    at registration (import) time, like core.init_gflags."""
    typ = typ or type(default)
    _TYPES[name] = (typ, on_set)
    val = default
    env = os.environ.get("FLAGS_" + name)
    if env is not None:
        val = _parse(env, typ)
    _FLAGS[name] = val
    if on_set is not None and env is not None:
        on_set(val)


def _parse(s, typ):
    if typ is bool:
        return s.strip().lower() in ("1", "true", "yes", "on")
    return typ(s)


def set_flags(flags):
    """set_flags({'FLAGS_check_nan_inf': True}) — accepts both the
    FLAGS_-prefixed spelling (reference API) and the bare name."""
    with _mu:
        for k, v in flags.items():
            name = k[6:] if k.startswith("FLAGS_") else k
            if name not in _FLAGS:
                raise KeyError("unknown flag %r" % k)
            typ, on_set = _TYPES[name]
            v = _parse(v, typ) if isinstance(v, str) else typ(v)
            _FLAGS[name] = v
            if on_set is not None:
                on_set(v)


def get_flags(names):
    """get_flags('FLAGS_check_nan_inf') or a list; returns dict."""
    if isinstance(names, str):
        names = [names]
    out = {}
    for k in names:
        name = k[6:] if k.startswith("FLAGS_") else k
        if name not in _FLAGS:
            raise KeyError("unknown flag %r" % k)
        out[k] = _FLAGS[name]
    return out


def flag(name):
    """Fast internal accessor."""
    return _FLAGS[name]


def _on_debug_nans(val):
    import jax

    jax.config.update("jax_debug_nans", bool(val))


def _on_cpu_deterministic(val):
    import jax

    # deterministic reductions: disable non-deterministic fast paths
    jax.config.update("jax_default_matmul_precision",
                      "highest" if val else None)


register_flag("check_nan_inf", False, bool)
# opt-in hand-tiled Pallas kernels for hot ops (ops/pallas/)
register_flag("pallas_kernels", False, bool)
# rbg counter PRNG for in-graph randomness (dropout masks etc.):
# cheaper random bits on TPU than the default threefry; different (but
# still deterministic-per-seed) random streams.  Fetch-synced A/B on the
# bench transformer: +34% tokens/s (threefry dropout masks were ~25% of
# the step) — the bench enables it; default off for stream stability.
register_flag("fast_prng", False, bool)
# exact two-pass batch_norm variance (E[(x-mean)^2]) instead of the
# default fused one-pass E[x^2]-E[x]^2 form; costs one extra full
# activation read per BN (see ops/norm.py)
register_flag("bn_two_pass", False, bool)
# sequence-length gate for the flash-attention Pallas kernel: longer
# sequences fall back to the XLA attention (see
# ops/pallas/flash_attention.supported)
register_flag("pallas_attention_max_seq", 2048, int)
def _on_compile_cache_dir(val):
    from . import compile_cache

    compile_cache.enable_persistent_cache(val)


register_flag("debug_nans", False, bool, _on_debug_nans)
register_flag("benchmark", False, bool)
# persistent XLA compilation cache directory ("" = disabled): repeated
# program+signature shapes across bench rungs, restarts, and tests
# deserialize the compiled executable instead of re-running the XLA
# pipeline (see compile_cache.py)
register_flag("compile_cache_dir", "", str, _on_compile_cache_dir)
# async-dispatch window: how many steps the host may run ahead of the
# device before blocking on the oldest in-flight step's fetches
# (return_numpy=False paths).  Bounds host run-ahead and device-buffer
# liveness; syncs happen only at window edges.
register_flag("max_inflight_steps", 8, int)
register_flag("cpu_deterministic", False, bool, _on_cpu_deterministic)
# accepted for API parity; memory is managed by XLA (VERDICT #1):
register_flag("eager_delete_tensor_gb", -1.0, float)
register_flag("fraction_of_gpu_memory_to_use", 0.92, float)


def _on_monitor_change(_val):
    # one reconcile hook for the whole FLAGS_monitor* family: the
    # monitor re-reads every flag and starts/stops/reconfigures only the
    # components whose config changed
    from . import monitor

    monitor._reconcile()


# always-on telemetry (monitor/): the master switch...
register_flag("monitor", False, bool, _on_monitor_change)
# ...and the exporter knobs — setting any of the log dir, the port, or
# the console interval implies the switch: a rotating JSONL
# StepStats/event log directory ("" = off),
register_flag("monitor_log_dir", "", str, _on_monitor_change)
# a Prometheus-style /metrics HTTP endpoint (0 = off),
register_flag("monitor_port", 0, int, _on_monitor_change)
# and a periodic one-line console summary interval (0 = off).
register_flag("monitor_console_seconds", 0.0, float, _on_monitor_change)
# The watchdog's stall window CONFIGURES but does not imply (its default
# is non-zero): with the monitor on and no step completed for this long,
# dump queue states + heartbeats + last span to stderr and the event log
# (0 = watchdog off)
register_flag("monitor_stall_seconds", 120.0, float, _on_monitor_change)
def _on_preflight_oom(val):
    # validate at set time: a typo ("stric") silently downgrading the
    # hard-fail mode to a warning would defeat the operator's intent
    allowed = ("auto", "warn", "strict", "off", "0", "false", "no",
               "none", "")
    if str(val).strip().lower() not in allowed:
        raise ValueError(
            "FLAGS_preflight_oom must be one of auto/warn/strict/off, "
            "got %r" % (val,))


# HBM preflight (monitor/program_profile.py): before the first dispatch
# of a newly compiled program, compare its estimated peak device memory
# (from the compiled module's own memory_analysis) against device
# capacity.  "auto" (default) rides along whenever the monitor is on
# (profile capture is monitor-gated) and warns; "warn"/"strict" force
# capture + preflight even on unmonitored runs, warning or raising
# PreflightOOMError instead of letting XLA OOM mid-run; "off" disables
# the check (profiles still capture while the monitor is on).
register_flag("preflight_oom", "auto", str, _on_preflight_oom)
# capacity override in bytes for the preflight (0 = use the device's
# memory_stats()['bytes_limit']; useful in tests and on backends that
# misreport capacity)
register_flag("preflight_hbm_bytes", 0, int)
