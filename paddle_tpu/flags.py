"""Global runtime flags — the gflags/env-whitelist analog.

Parity: the reference defines C++ gflags next to each subsystem
(``FLAGS_check_nan_inf`` in ``framework/operator.cc:31``,
``FLAGS_benchmark`` in ``framework/executor.cc:396``,
``FLAGS_cpu_deterministic``) and re-exports an env-settable whitelist at
import time (``python/paddle/fluid/__init__.py:112-126`` →
``core.init_gflags``).  Here flags are a typed registry: each flag has a
declared type and default, is overridable from the environment at import
(``FLAGS_<name>=...``) and at runtime via ``set_flags``/``get_flags``.

TPU-native semantics of the debugging flags:

* ``check_nan_inf`` — after every executor step, block on the step's
  outputs and verify finiteness of all floating fetches and written-back
  state; raise naming the first offending variable.  (The reference
  checks every op's outputs inside the interpreter loop,
  ``operator.cc:717``; under whole-program jit the step boundary is the
  observable granularity.)
* ``debug_nans`` — op-level localization: enables ``jax_debug_nans``,
  which re-runs a nan-producing jitted step op-by-op to point at the
  guilty primitive.  Finer-grained but globally intrusive; separate
  from ``check_nan_inf`` so the cheap step-level check doesn't flip
  global jax config.
* ``cpu_deterministic`` — forces deterministic XLA reductions
  (``--xla_cpu_enable_fast_math=false`` analog) via jax config.
* ``benchmark`` — per-step wall-clock logging in the executors.

Robustness families (ISSUE 8): the ``FLAGS_guardian_*`` family
configures the training-run guardian (``guardian.py``: in-graph NaN/Inf
skip guard, loss spike/plateau detection, skip -> rollback -> abort
recovery ladder with budgets, quarantine directory, watchdog-stall
escalation) and the ``FLAGS_fault_*`` family installs deterministic
fault-injection drills (``fault.py``: seed/step-indexed schedules for
NaN vars, poisoned batches, dispatch delay/failure, mid-save kills)
from a spec string — each flag is documented at its registration below.
"""

import os
import threading

__all__ = ["set_flags", "get_flags", "register_flag", "pinned"]

_mu = threading.Lock()
_FLAGS = {}
_TYPES = {}
# flags the OPERATOR set explicitly (env override at import, or
# set_flags with the default pin=True): the auto-tuner's decisions
# (autotune.py) defer to pinned flags — an explicit user choice always
# beats a tuned one.  Internal machinery that flips flags on the user's
# behalf without expressing a preference (the tuner's own A/B arms)
# passes pin=False.
_PINNED = set()


def register_flag(name, default, typ=None, on_set=None):
    """Declare a flag.  Env var ``FLAGS_<name>`` overrides the default
    at registration (import) time, like core.init_gflags."""
    typ = typ or type(default)
    _TYPES[name] = (typ, on_set)
    val = default
    env = os.environ.get("FLAGS_" + name)
    if env is not None:
        val = _parse(env, typ)
        _PINNED.add(name)
    _FLAGS[name] = val
    if on_set is not None and env is not None:
        on_set(val)


def _parse(s, typ):
    if typ is bool:
        return s.strip().lower() in ("1", "true", "yes", "on")
    return typ(s)


def set_flags(flags, pin=True):
    """set_flags({'FLAGS_check_nan_inf': True}) — accepts both the
    FLAGS_-prefixed spelling (reference API) and the bare name.

    ``pin=True`` (the default) marks each flag as an explicit operator
    choice (see :func:`pinned`): the auto-tuner never overrides a
    pinned flag.  ``pin=False`` is for machinery — the tuner's own A/B
    arms, restore-after paths — that sets values without expressing a
    preference."""
    with _mu:
        for k, v in flags.items():
            name = k[6:] if k.startswith("FLAGS_") else k
            if name not in _FLAGS:
                raise KeyError("unknown flag %r" % k)
            typ, on_set = _TYPES[name]
            v = _parse(v, typ) if isinstance(v, str) else typ(v)
            prev = _FLAGS[name]
            _FLAGS[name] = v
            if on_set is not None:
                try:
                    on_set(v)
                except Exception:
                    # a raising validator (guardian_policy, fault_spec,
                    # ...) must not leave the rejected value readable
                    # via flag().  Commit-then-rollback (not validate-
                    # first) because reconcile-style hooks re-read
                    # their own flag (_on_monitor_change).
                    _FLAGS[name] = prev
                    raise
            if pin:
                _PINNED.add(name)


def get_flags(names):
    """get_flags('FLAGS_check_nan_inf') or a list; returns dict."""
    if isinstance(names, str):
        names = [names]
    out = {}
    for k in names:
        name = k[6:] if k.startswith("FLAGS_") else k
        if name not in _FLAGS:
            raise KeyError("unknown flag %r" % k)
        out[k] = _FLAGS[name]
    return out


def flag(name):
    """Fast internal accessor."""
    return _FLAGS[name]


def pinned(name):
    """Whether the operator set this flag explicitly (env override at
    import, or ``set_flags`` with the default ``pin=True``).  The
    auto-tuner (``autotune.py``) consults this before applying any
    flag-backed decision: a pinned flag always wins over the tuner."""
    name = name[6:] if name.startswith("FLAGS_") else name
    if name not in _FLAGS:
        raise KeyError("unknown flag %r" % name)
    return name in _PINNED


def _restore_pins(mapping):
    """Restore a saved {name: was_pinned} snapshot (the tuner's A/B
    arms save pins, flip flags unpinned, and put the world back)."""
    with _mu:
        for name, was in mapping.items():
            (_PINNED.add if was else _PINNED.discard)(name)


def _on_debug_nans(val):
    import jax

    jax.config.update("jax_debug_nans", bool(val))


def _on_cpu_deterministic(val):
    import jax

    # deterministic reductions: disable non-deterministic fast paths
    jax.config.update("jax_default_matmul_precision",
                      "highest" if val else None)


register_flag("check_nan_inf", False, bool)
# opt-in hand-tiled Pallas kernels for hot ops (ops/pallas/)
register_flag("pallas_kernels", False, bool)
# rbg counter PRNG for in-graph randomness (dropout masks etc.):
# cheaper random bits on TPU than the default threefry; different (but
# still deterministic-per-seed) random streams.  Fetch-synced A/B on the
# bench transformer: +34% tokens/s (threefry dropout masks were ~25% of
# the step) — the bench enables it; default off for stream stability.
register_flag("fast_prng", False, bool)
# exact two-pass batch_norm variance (E[(x-mean)^2]) instead of the
# default fused one-pass E[x^2]-E[x]^2 form; costs one extra full
# activation read per BN (see ops/norm.py)
register_flag("bn_two_pass", False, bool)
# sequence-length gate for the flash-attention Pallas kernel: longer
# sequences fall back to the XLA attention (see
# ops/pallas/flash_attention.supported)
register_flag("pallas_attention_max_seq", 2048, int)
def _on_compile_cache_dir(val):
    from . import compile_cache

    compile_cache.enable_persistent_cache(val)


register_flag("debug_nans", False, bool, _on_debug_nans)
register_flag("benchmark", False, bool)
# persistent XLA compilation cache directory ("" = disabled): repeated
# program+signature shapes across bench rungs, restarts, and tests
# deserialize the compiled executable instead of re-running the XLA
# pipeline (see compile_cache.py)
register_flag("compile_cache_dir", "", str, _on_compile_cache_dir)
# async-dispatch window: how many steps the host may run ahead of the
# device before blocking on the oldest in-flight step's fetches
# (return_numpy=False paths).  Bounds host run-ahead and device-buffer
# liveness; syncs happen only at window edges.
register_flag("max_inflight_steps", 8, int)
register_flag("cpu_deterministic", False, bool, _on_cpu_deterministic)
# accepted for API parity; memory is managed by XLA (VERDICT #1):
register_flag("eager_delete_tensor_gb", -1.0, float)
register_flag("fraction_of_gpu_memory_to_use", 0.92, float)


def _on_monitor_change(_val):
    # one reconcile hook for the whole FLAGS_monitor* family: the
    # monitor re-reads every flag and starts/stops/reconfigures only the
    # components whose config changed
    from . import monitor

    monitor._reconcile()


# always-on telemetry (monitor/): the master switch...
register_flag("monitor", False, bool, _on_monitor_change)
# ...and the exporter knobs — setting any of the log dir, the port, or
# the console interval implies the switch: a rotating JSONL
# StepStats/event log directory ("" = off),
register_flag("monitor_log_dir", "", str, _on_monitor_change)
# a Prometheus-style /metrics HTTP endpoint (0 = off),
register_flag("monitor_port", 0, int, _on_monitor_change)
# and a periodic one-line console summary interval (0 = off).
register_flag("monitor_console_seconds", 0.0, float, _on_monitor_change)
# The watchdog's stall window CONFIGURES but does not imply (its default
# is non-zero): with the monitor on and no step completed for this long,
# dump queue states + heartbeats + last span to stderr and the event log
# (0 = watchdog off)
register_flag("monitor_stall_seconds", 120.0, float, _on_monitor_change)


def _on_trace_change(_val):
    from .monitor import tracing

    tracing._reconcile()


# per-request distributed tracing (monitor/tracing.py): span trees over
# the serving lifecycle + cluster RPC.  Independent of FLAGS_monitor —
# spans always land in the in-process buffer; a JSONL twin is written
# whenever FLAGS_monitor_log_dir is also set.
register_flag("trace", False, bool, _on_trace_change)


def _on_fleet_telemetry_change(_val):
    from .monitor import aggregate

    aggregate._reconcile()


def _on_health_change(_val):
    from .monitor import health

    health._reconcile()


# model-health telemetry (monitor/health.py): with it on, the executors
# lower steps with an in-graph per-layer probe (grad L2 norm, param
# norm, update/param ratio, non-finite count as one extra fetch) and
# stash per-step NaN-provenance replay contexts.  Baked into the traced
# jaxpr — flipping it re-keys the trace caches.  Disabled cost is zero
# health calls (module-global bool; A/B test-enforced) and the seeded
# training trajectory is bit-identical with the flag on or off.
register_flag("health", False, bool, _on_health_change)
# host-side publication cadence for the probe: the stats are computed
# on-device every step (fused, no sync), but gauges + model_health
# JSONL records publish every Nth step — the only host sync the probe
# adds
register_flag("health_every", 10, int, _on_health_change)


# fleet telemetry plane (monitor/aggregate.py): each ClusterMember ships
# a MetricDigest on its existing heartbeat; the master merges digests
# into fleet-level series, straggler verdicts, and SLO alerts.  Off by
# default — the disabled path is one module-global bool read.
register_flag("fleet_telemetry", False, bool, _on_fleet_telemetry_change)
# digest byte budget per heartbeat: over it, oldest step samples and
# lowest-traffic histograms decimate (counted in fleet/digest_truncated)
# so a fat digest never delays lease renewal
register_flag("fleet_digest_bytes", 16384, int, _on_fleet_telemetry_change)


def _on_preflight_oom(val):
    # validate at set time: a typo ("stric") silently downgrading the
    # hard-fail mode to a warning would defeat the operator's intent
    allowed = ("auto", "warn", "strict", "off", "0", "false", "no",
               "none", "")
    if str(val).strip().lower() not in allowed:
        raise ValueError(
            "FLAGS_preflight_oom must be one of auto/warn/strict/off, "
            "got %r" % (val,))


# HBM preflight (monitor/program_profile.py): before the first dispatch
# of a newly compiled program, compare its estimated peak device memory
# (from the compiled module's own memory_analysis) against device
# capacity.  "auto" (default) rides along whenever the monitor is on
# (profile capture is monitor-gated) and warns; "warn"/"strict" force
# capture + preflight even on unmonitored runs, warning or raising
# PreflightOOMError instead of letting XLA OOM mid-run; "off" disables
# the check (profiles still capture while the monitor is on).
register_flag("preflight_oom", "auto", str, _on_preflight_oom)
# capacity override in bytes for the preflight (0 = use the device's
# memory_stats()['bytes_limit']; useful in tests and on backends that
# misreport capacity)
register_flag("preflight_hbm_bytes", 0, int)


def _on_guardian_policy(val):
    # validate at set time: a typo'd rung ("rolback") silently dropping
    # rollback from the ladder would defeat the operator's intent
    bad = {t.strip() for t in str(val).split(",") if t.strip()} \
        - {"skip", "rollback", "abort"}
    if bad:
        raise ValueError(
            "FLAGS_guardian_policy tokens must be among "
            "skip/rollback/abort, got %s" % sorted(bad))


def _on_guardian_spike_action(val):
    if str(val).strip() not in ("warn", "rollback", "off"):
        raise ValueError(
            "FLAGS_guardian_spike_action must be warn/rollback/off, "
            "got %r" % (val,))


# Training-run guardian (guardian.py): the master switch.  With it on,
# the contrib Trainer installs a Guardian by default, both executors
# feed it every step, and — when the policy ladder includes "skip" —
# steps are lowered with the in-graph NaN/Inf guard (non-finite fetched
# losses suppress the state update on-device).  Flipping it re-keys the
# trace caches (the guard is baked into the jaxpr).  Disabled cost is
# one flag/module-global read per step (A/B test-enforced).
register_flag("guardian", False, bool)
# the recovery ladder, ordered mildest-first: "skip" (in-graph drop of
# the offending update + batch quarantine), "rollback" (restore the
# newest clean TrainState and replay), "abort" (typed
# GuardianAbortError once the rollback budget is spent).  Comma-joined
# subset of skip/rollback/abort.
register_flag("guardian_policy", "skip,rollback,abort", str,
              _on_guardian_policy)
# rolling-window size for the loss spike/plateau detector (median+MAD
# over the last N finite losses)
register_flag("guardian_window", 32, int)
# spike threshold: |loss - median| / (1.4826*MAD) above this z-score is
# an anomaly (robust z; 8 is far out on any well-behaved loss curve)
register_flag("guardian_zmax", 8.0, float)
# consecutive in-graph-skipped steps before the ladder escalates to
# rollback (a burst of bad batches is data trouble, not a blip)
register_flag("guardian_max_skips", 8, int)
# rollback attempts before GuardianAbortError — the bound that turns
# "recover forever" into a typed failure
register_flag("guardian_max_rollbacks", 2, int)
# where quarantined batches (offending feed + signature + run_id) are
# written for repro ("" = record the signature in the event log only;
# the contrib Trainer defaults this to <checkpoint_dir>/quarantine)
register_flag("guardian_quarantine_dir", "", str)
# what a detected loss spike does: "warn" (event+counter only),
# "rollback" (escalate like a non-finite loss), "off"
register_flag("guardian_spike_action", "warn", str,
              _on_guardian_spike_action)
# plateau detector window (0 = off): no median improvement across the
# last N losses publishes a guardian_plateau event (advisory only)
register_flag("guardian_plateau_steps", 0, int)
# consecutive watchdog stall windows before the guardian arms a typed
# abort (0 = never escalate stalls)
register_flag("guardian_stall_escalations", 3, int)


def _on_fault_spec(val):
    # install drills straight from the environment/set_flags: the
    # env-var entry point that makes a fault drill runnable against any
    # existing script (FLAGS_fault_spec="nan_var:fc_0.w_0@5;..." ).
    # install_from_spec REPLACES the previous spec's hooks, so the
    # installed fault state always mirrors the flag value; an empty
    # value disarms a previously set spec (nothing to disarm — and no
    # reason to import fault — if fault.py was never imported).
    if not str(val).strip():
        import sys
        fault = sys.modules.get(__name__.rsplit(".", 1)[0] + ".fault")
        if fault is not None and hasattr(fault, "install_from_spec"):
            fault.install_from_spec("")
        return
    from . import fault

    if not hasattr(fault, "install_from_spec"):
        # registration-time env override while fault.py is mid-import
        # (fault -> flags -> this hook): fault installs the env spec
        # itself at the end of its module body
        return
    fault.install_from_spec(val)


# Profile-guided auto-configuration (autotune.py): where TunedConfig
# artifacts and the persistent attention-kernel decision table live
# ("" = decision table stays in-memory only; warm processes then
# re-measure)
register_flag("autotune_dir", "", str)
# device-memory ceiling override in bytes for the tuner's batch-size
# probe (0 = fall back to FLAGS_preflight_hbm_bytes, then the device's
# memory_stats()['bytes_limit']).  The probe rejects candidates by the
# compiled module's own peak-HBM ESTIMATE against this ceiling — never
# by an OOM crash — which is what makes the ladder testable on CPU
# with a fake limit.
register_flag("autotune_hbm_bytes", 0, int)
# checkpoint-cadence overhead budget (CheckFreq-style): the tuner picks
# the smallest save interval whose measured on-step checkpoint cost
# stays under this fraction of compute
register_flag("autotune_overhead_budget", 0.035, float)
def _on_quantize_mode(val):
    if str(val).strip() not in ("", "off", "weight_only", "dynamic"):
        raise ValueError(
            "FLAGS_quantize_mode must be one of ''/off/weight_only/"
            "dynamic, got %r" % (val,))


# Quantized inference (transpiler.quantize_inference + autotune.
# tune_quantization): an explicit mode is the operator's choice — the
# accuracy-gated tuner records it as pinned and never measures over it
# ("off" pins full precision; "" leaves the decision to the tuner)
register_flag("quantize_mode", "", str, _on_quantize_mode)
# accuracy budget for the quantization gate: the tuner only keeps a
# quantized program whose eval delta (relative L1 over the A/B fetches)
# stays under this fraction; rejections are recorded as TunedConfig
# evidence and full precision is kept
register_flag("quantize_accuracy_budget", 0.02, float)
# seed for probabilistic fault schedules (prob=...): two runs with the
# same seed inject at identical steps.  Registered BEFORE fault_spec:
# an env-set spec installs schedules at import, which read this flag.
register_flag("fault_seed", 0, int)
# deterministic fault-injection drills (fault.py), installed from a
# spec string: family:arg@schedule[;...] — see fault.install_from_spec
# for the grammar and drill families (nan_var, poison_batch, delay,
# fail_dispatch, kill_save)
register_flag("fault_spec", "", str, _on_fault_spec)
