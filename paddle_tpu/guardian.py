"""Training-run guardian (ISSUE 8 tentpole, part 1): anomaly sentinels
plus an automatic recovery ladder over the TrainState checkpoints.

PR 2-4 built the pieces — observability, exact-resume checkpoints, a
watchdog — but every recovery was manual: a NaN step raised and killed
the run, a loss spike waited for a human to read the JSONL.  The
guardian closes the detect -> decide -> recover loop (the CheckFreq /
Check-N-Run argument: the checkpoint subsystem's value is realized only
when recovery is automatic and cheap; see PAPERS.md):

**Sentinels**

* an **in-graph NaN/Inf guard** (``wrap_step_guard``): when the policy
  ladder includes ``skip``, the executors trace the step with a
  finiteness check over the floating fetches (loss, grad-norm — the
  fetched health signals) and *suppress the state update in-graph*
  (``where(ok, new, old)``) when any is non-finite.  A poisoned batch
  therefore never touches the parameters, the skip is exact (including
  LR/step counters), and — because the decision happens on-device
  before the host ever observes the loss — the post-recovery trajectory
  is bit-identical whether the host runs synchronously or
  ``return_numpy=False`` async (test-enforced).  Cost: one fused
  ``isfinite``-reduce per float fetch + a select per state var.
* a **host-side sentinel** in ``observe``/``note_step``: non-finite
  observed losses that the in-graph guard could not prevent (already
  NaN parameters, host-injected corruption) escalate straight to
  rollback;
* a **rolling-window spike/plateau detector**: median + MAD z-score
  over the last ``window`` finite losses (robust to the very outliers
  it hunts); spikes publish events and optionally roll back
  (``spike_action``), plateaus publish events;
* **stall escalation**: the guardian subscribes to the Watchdog's stall
  firings (``monitor.add_stall_listener``); after
  ``stall_escalations`` consecutive stall windows with no completed
  step it arms an abort that the next observed step raises — a wedged
  pipeline becomes a typed error, not an eternal hang.

**Recovery ladder** (``policy``, default ``skip,rollback,abort``):

1. *skip-step* — the in-graph guard drops the offending update; the
   host quarantines the batch to disk (feed signature + run_id, for
   repro) and counts it.  More than ``max_skips`` consecutive skips
   escalate.
2. *rollback* — raise ``GuardianRollback``; the driver (the contrib
   Trainer, or any caller) restores the newest *clean* TrainState at or
   below the failure (NaN-poisoned or corrupt artifacts are skipped),
   rewinds the executor PRNG counter and reader position through the
   PR 4 exact-resume machinery, and — when the failure was quarantined
   batches — fast-forwards the reader past the poisoned window so the
   replay makes progress instead of re-tripping.
3. *abort* — after ``max_rollbacks`` rollbacks, raise
   ``GuardianAbortError`` (typed; never an unbounded recover loop).

Every decision is published: ``guardian/skipped_steps``,
``guardian/rollbacks``, ``guardian/quarantined_batches``,
``guardian/loss_spikes``, ``guardian/stall_escalations`` counters and
``guardian_*`` JSONL events, all run_id-stamped so they join against
step records, traces, and fault injections.

Disabled cost is one module-global read per executor step
(``active()`` is None), same contract as ``monitor.enabled()`` —
A/B-test-enforced.
"""

import collections
import json
import os
import time
import warnings

import numpy as np

from . import flags

__all__ = [
    "Guardian", "GuardianRollback", "GuardianAbortError",
    "install", "uninstall", "active", "installed",
    "skip_guard_enabled", "wrap_step_guard",
]


class GuardianRollback(RuntimeError):
    """Control-flow signal: the guardian decided the run must roll back
    to the last clean checkpoint.  Carries the failing step index, the
    reason, and whether quarantined batches implicate the data (the
    replay then fast-forwards past the poisoned window)."""

    def __init__(self, step, reason, quarantined=False):
        super().__init__(
            "guardian: rollback requested at step %d (%s)" % (step, reason))
        self.step = int(step)
        self.reason = reason
        self.quarantined = bool(quarantined)


class GuardianAbortError(RuntimeError):
    """The recovery ladder is exhausted (rollback budget spent, no clean
    checkpoint, or watchdog-stall escalation): the run must stop with a
    typed error instead of looping or hanging."""


def _policy_tokens(policy=None):
    policy = policy if policy is not None else flags.flag("guardian_policy")
    return tuple(t.strip() for t in str(policy).split(",") if t.strip())


def skip_guard_enabled():
    """Whether the executors lower steps with the in-graph skip guard:
    the guardian flag is on and ``skip`` is in the policy ladder — the
    INSTALLED guardian's ladder when one is active (an instance policy
    of ``rollback,abort`` must not leave a flag-level skip guard
    deciding differently), else ``FLAGS_guardian_policy``.  Baked into
    the traced jaxpr, so it is part of
    ``compile_cache.trace_flag_values()`` (and therefore of every
    compile-cache key: installing a guardian re-keys, never serves a
    stale unguarded trace)."""
    if not flags.flag("guardian"):
        return False
    g = _ACTIVE
    policy = g.policy if g is not None else _policy_tokens()
    return "skip" in policy


def wrap_step_guard(fn, state_in, state_out, n_watch=None):
    """Wrap a traced step function with the in-graph sentinel + skip:
    ``ok`` = every floating fetch is finite; state vars that existed
    before the step keep their OLD value when ``ok`` is false (the
    update — params, optimizer slots, LR/step counters — is dropped
    atomically); write-only outputs (first-step initializations) pass
    through.  Returns ``fetches + [ok]``: the executors strip the
    trailing ``ok`` and hand it to the active guardian.

    ``n_watch`` bounds the sentinel to the first N fetches: the health
    probe (monitor/health.py) appends ``@GRAD`` extras after the user
    fetches, and a gradient that overflowed must trip the guard through
    the loss it poisons, not through a diagnostic fetch — guard
    semantics are identical with the probe on or off.  None watches
    everything (the pre-probe behavior)."""
    import jax.numpy as jnp

    idx = {n: i for i, n in enumerate(state_in)}

    def guarded(feed_vals, state_vals, key):
        fetches, new_state = fn(feed_vals, state_vals, key)
        watched = fetches if n_watch is None else fetches[:n_watch]
        ok = jnp.asarray(True)
        for f in watched:
            if jnp.issubdtype(jnp.result_type(f), jnp.inexact):
                ok = jnp.logical_and(ok, jnp.isfinite(f).all())
        new_state = [
            jnp.where(ok, nv, state_vals[idx[n]]) if n in idx else nv
            for n, nv in zip(state_out, new_state)
        ]
        return list(fetches) + [ok], new_state

    return guarded


def warn_unobserved_skip_guard(executor):
    """Called by an executor whose step came back with a guard verdict
    (``ok`` fetch) while no guardian is installed to decide on it:
    non-finite updates are being dropped on-device with no event,
    counter, or budget.  Legal, but almost always a leaked
    ``FLAGS_guardian`` — say so once per executor."""
    if getattr(executor, "_warned_unobserved_guard", False):
        return
    executor._warned_unobserved_guard = True
    warnings.warn(
        "in-graph skip guard is active (FLAGS_guardian) but no "
        "guardian is installed: non-finite updates are dropped "
        "silently — install one (guardian.install / Trainer "
        "guardian_config) or clear FLAGS_guardian")


def _provenance_clause(prov):
    """Render a NaN-provenance record into an escalation-message clause
    ('' when provenance is unavailable or found nothing)."""
    if not prov or not prov.get("found"):
        return ""
    layer = prov.get("layer")
    return "; first non-finite op: %s -> %r (op #%d%s)" % (
        prov.get("op_type"), prov.get("out_var"),
        prov.get("op_index", -1),
        ", layer %s" % layer if layer else "")


def _finite(a):
    from .fault import _floatish

    a = np.asarray(a)
    if not np.issubdtype(a.dtype, np.floating):
        if not _floatish(a.dtype):
            return True              # integral state cannot go non-finite
        # bf16/float8 etc. (ml_dtypes): np.isfinite lacks a loop
        a = a.astype(np.float32)
    return bool(np.isfinite(a).all())


def _ready(v):
    """Non-blocking readiness: numpy / None are ready; a jax Array is
    ready when its device computation retired."""
    if v is None:
        return True
    is_ready = getattr(v, "is_ready", None)
    return True if is_ready is None else bool(is_ready())


class Guardian:
    """Per-run anomaly sentinel + recovery policy.  Construction reads
    the ``FLAGS_guardian_*`` family; kwargs override per-instance (the
    Trainer's ``guardian_config`` path).  ``install`` it (or pass it to
    the Trainer) to have both executors feed it every step."""

    def __init__(self, policy=None, window=None, zmax=None,
                 max_skips=None, max_rollbacks=None, quarantine_dir=None,
                 spike_action=None, plateau_steps=None,
                 stall_escalations=None, loss_name=None):
        self.policy = _policy_tokens(policy)
        bad = set(self.policy) - {"skip", "rollback", "abort"}
        if bad:
            raise ValueError("unknown guardian policy tokens %s "
                             "(know: skip, rollback, abort)" % sorted(bad))
        self.window = int(window if window is not None
                          else flags.flag("guardian_window"))
        self.zmax = float(zmax if zmax is not None
                          else flags.flag("guardian_zmax"))
        self.max_skips = int(max_skips if max_skips is not None
                             else flags.flag("guardian_max_skips"))
        self.max_rollbacks = int(
            max_rollbacks if max_rollbacks is not None
            else flags.flag("guardian_max_rollbacks"))
        self.quarantine_dir = (
            quarantine_dir if quarantine_dir is not None
            else flags.flag("guardian_quarantine_dir"))
        self.spike_action = str(
            spike_action if spike_action is not None
            else flags.flag("guardian_spike_action"))
        if self.spike_action not in ("warn", "rollback", "off"):
            raise ValueError("spike_action must be warn/rollback/off, "
                             "got %r" % self.spike_action)
        self.plateau_steps = int(
            plateau_steps if plateau_steps is not None
            else flags.flag("guardian_plateau_steps"))
        self.stall_escalations = int(
            stall_escalations if stall_escalations is not None
            else flags.flag("guardian_stall_escalations"))
        self.loss_name = loss_name
        self.reset_run_state()

    def reset_run_state(self):
        """Start a fresh run segment: detection history, budgets, and
        counters are PER-RUN — a Guardian instance reused across
        ``train()`` calls must not carry a spent rollback budget or an
        armed stall abort into the next run (the Trainer calls this
        when it re-installs a caller-provided instance)."""
        # deferred observations: (step, ok handle, loss handle, feed)
        # — drained when their device values are ready (non-blocking) or
        # when the deque outgrows the dispatch window, so the async fast
        # path keeps its overlap while decisions stay deterministic
        # (the skip itself already happened in-graph)
        self._pending = collections.deque()
        # history must hold plateau_steps losses too: a plateau window
        # longer than the spike window would otherwise never fill and
        # the detector would be silently dead
        self._history = collections.deque(
            maxlen=max(4, self.window, self.plateau_steps))
        self._consecutive_skips = 0
        self._spike_run = 0          # consecutive spike-flagged steps
        self._rollbacks = 0
        self._stalls = 0
        self._stall_abort = None
        self._plateau_armed = True
        self.skipped_steps = 0
        self.quarantined = []        # [(step, reason)] this run segment
        # measured replay debt of the last rollback (failed step minus
        # restored step): the checkpoint-interval tuner's evidence
        self.last_replay_steps = None

    # -- executor hook -------------------------------------------------
    def note_step(self, executor_name, step, ok=None, fetch_names=(),
                  fetches=(), feed=None, sync=False):
        """One executor step completed.  ``ok`` is the in-graph guard's
        verdict handle (None when the guard is off), ``fetches`` the
        user-visible fetch values (device arrays on the async path),
        ``feed`` a ``(names, values)`` pair for quarantine.  Raises
        ``GuardianRollback``/``GuardianAbortError`` per the policy
        ladder — from inside ``run()``, so the training loop sees the
        decision at the step that made it observable."""
        if self._stall_abort is not None:
            reason, self._stall_abort = self._stall_abort, None
            raise GuardianAbortError(reason)
        self._stalls = 0            # a completed step re-arms escalation
        loss = self._watched_fetch(fetch_names, fetches)
        self._pending.append((int(step), ok, loss, feed))
        self._drain(force=sync)

    def flush(self):
        """Force-process every deferred observation (epoch boundaries,
        end of run) — blocks on any not-yet-retired step handles.  The
        ladder's exceptions can raise from here."""
        self._drain(force=True)

    def _watched_fetch(self, fetch_names, fetches):
        if self.loss_name is not None:
            for n, f in zip(fetch_names, fetches):
                if n == self.loss_name:
                    return f
            return None
        for f in fetches:
            dt = getattr(f, "dtype", None)
            if dt is not None and np.issubdtype(
                    np.dtype(dt) if not isinstance(dt, np.dtype) else dt,
                    np.inexact):
                return f
        return None

    def _max_pending(self):
        return max(1, int(flags.flag("max_inflight_steps")))

    def _drain(self, force):
        while self._pending:
            step, ok, loss, feed = self._pending[0]
            if not force and len(self._pending) <= self._max_pending() \
                    and not (_ready(ok) and _ready(loss)):
                return
            self._pending.popleft()
            self._process(step, ok, loss, feed)

    # -- decision core -------------------------------------------------
    def _process(self, step, ok, loss, feed):
        ok_v = None if ok is None else bool(np.asarray(ok))
        if ok_v is False:
            self._on_skip(step, feed)
            return
        if loss is not None and not _finite(loss):
            self._on_nonfinite(step, feed)
            return
        self._consecutive_skips = 0
        if loss is not None:
            self._observe_loss(step, float(np.mean(np.asarray(
                loss, dtype=np.float64))))

    def _on_skip(self, step, feed):
        self.skipped_steps += 1
        self._consecutive_skips += 1
        self._counter("guardian/skipped_steps")
        q = self._quarantine(step, feed, "nonfinite_in_graph")
        prov = self._provenance(step, q)
        self._event({"event": "guardian_skip", "step": step,
                     "consecutive": self._consecutive_skips,
                     "quarantine": q})
        if self._consecutive_skips > self.max_skips:
            self._escalate(step,
                           "%d consecutive in-graph skips exceed the "
                           "skip budget (%d)%s"
                           % (self._consecutive_skips, self.max_skips,
                              _provenance_clause(prov)),
                           quarantined=True)

    def _on_nonfinite(self, step, feed):
        q = self._quarantine(step, feed, "nonfinite_observed")
        prov = self._provenance(step, q)
        self._event({"event": "guardian_nonfinite", "step": step,
                     "quarantine": q})
        # the update already reached the scope (no in-graph guard, or
        # corruption past it): skipping cannot help — escalate
        self._escalate(step, "non-finite loss observed"
                       + _provenance_clause(prov), quarantined=False)

    def _provenance(self, step, q):
        """NaN provenance for a quarantined step (ISSUE 20): replay the
        already-quarantined batch through the debug-lowered op walk and
        name the first offending op.  The record is attached to the
        quarantine sidecar (JSON rewritten in place) and published as a
        ``guardian_nan_provenance`` event.  One health-module read when
        the probe is off; never raises — this runs on the abort path."""
        from .monitor import health

        if not health.enabled():
            return None
        try:
            # the stashed replay context holds the same feed values the
            # quarantine persisted (both executors hand note_step and
            # the guardian the identical pre-pad batch)
            prov = health.nan_provenance(step)
        except Exception:  # noqa: BLE001 — diagnostics must not mask
            return None
        if prov is None:
            return None
        q["provenance"] = prov
        if q.get("path"):
            try:
                with open(q["path"][: -len(".npz")] + ".json", "w") as f:
                    json.dump(q, f)
            except OSError:
                pass
        self._counter("guardian/nan_provenance")
        self._event(dict(prov, event="guardian_nan_provenance",
                         quarantine_path=q.get("path")))
        return prov

    def _observe_loss(self, step, loss):
        hist = self._history
        if len(hist) >= max(8, self.window // 2) and self.zmax > 0 \
                and self.spike_action != "off":
            # the deque may hold plateau_steps > window losses; the
            # spike baseline stays the last `window` of them
            base = np.asarray(list(hist)[-self.window:])
            med = float(np.median(base))
            mad = float(np.median(np.abs(base - med)))
            # the dispersion floor is RELATIVE to the loss scale: a
            # saturated window (MAD 0, e.g. a memorized or clamped
            # loss) must not turn float-noise fluctuations into
            # z ~ 1e4 spikes — below ~0.1% of the level there is no
            # anomaly to detect
            denom = 1.4826 * mad + 1e-4 * max(1.0, abs(med))
            # one-sided: only an UPWARD move is an anomaly — a sharp
            # improvement (LR-decay boundary, curriculum switch) is
            # healthy and enters the baseline like any other loss
            z = (loss - med) / denom
            floor = 1e-6 * max(1.0, abs(med))
            if z > self.zmax and loss - med > floor:
                self._spike_run += 1
                self._counter("guardian/loss_spikes")
                self._event({"event": "guardian_loss_spike", "step": step,
                             "loss": loss, "median": med, "mad": mad,
                             "z": round(z, 2), "action": self.spike_action})
                if self.spike_action == "rollback":
                    self._escalate(step,
                                   "loss spike z=%.1f (%.4g above %.4g "
                                   "over MAD %.4g)" % (z, loss, med, mad),
                                   quarantined=False)
                if self._spike_run <= max(2, self.window // 2):
                    return           # outliers stay out of the baseline
                # ... but boundedly: a level that persists for half a
                # window is the run's new regime, not a spike — restart
                # the baseline at it instead of flagging every remaining
                # step of the run against a frozen pre-shift median
                self._event({"event": "guardian_spike_baseline_reset",
                             "step": step, "loss": loss,
                             "outlier_run": self._spike_run})
                hist.clear()
                self._plateau_armed = True
            self._spike_run = 0
        hist.append(loss)
        self._check_plateau(step)

    def _check_plateau(self, step):
        n = self.plateau_steps
        if n <= 0 or len(self._history) < n:
            return
        recent = list(self._history)[-n:]
        first = float(np.median(recent[: n // 2]))
        second = float(np.median(recent[n // 2:]))
        improving = (first - second) > 1e-4 * max(1.0, abs(first))
        if improving:
            self._plateau_armed = True
        elif self._plateau_armed:
            self._plateau_armed = False    # fire once per plateau
            self._counter("guardian/plateaus")
            self._event({"event": "guardian_plateau", "step": step,
                         "window": n, "median_first_half": first,
                         "median_second_half": second})

    def _escalate(self, step, reason, quarantined):
        # abort/rollback diagnostics carry the last per-layer health
        # snapshot (ISSUE 20 satellite): the post-mortem's first
        # question — which layer was sick — is answered in the message
        from .monitor import health

        snap = health.format_snapshot()
        if snap:
            reason = "%s [health %s]" % (reason, snap)
        if "rollback" in self.policy:
            raise GuardianRollback(step, reason, quarantined=quarantined)
        raise GuardianAbortError(
            "guardian: %s at step %d and the policy ladder %r has no "
            "rollback rung" % (reason, step, ",".join(self.policy)))

    # -- rollback protocol (driven by the Trainer or any caller) -------
    def begin_rollback(self, rb):
        """Charge one rollback against the budget (raises
        ``GuardianAbortError`` when exhausted) before any restore work
        starts — the budget bounds ATTEMPTS, not successes."""
        self._rollbacks += 1
        self._counter("guardian/rollbacks")
        if self._rollbacks > self.max_rollbacks:
            raise GuardianAbortError(
                "guardian: rollback budget (%d) exhausted at step %d "
                "(%s) — the fault persists across recoveries; aborting "
                "instead of looping" % (self.max_rollbacks, rb.step,
                                        rb.reason))

    def rollback_restore(self, manager, rb, scope=None, program=None,
                         executors=None, readers=None, shardings=None):
        """Restore the newest CLEAN TrainState at or below the failed
        step: artifacts that are corrupt (checksum) or poisoned
        (non-finite arrays — a checkpoint taken after the corruption
        landed) are skipped with an event; a structural mismatch still
        raises (configuration error, not a fault).  Returns the
        restored step or raises ``GuardianAbortError`` when no clean
        artifact exists.  The whole scan+restore runs under a
        ``guardian/rollback`` span: the goodput ledger books it (plus
        the replayed steps after it) as ``recovery`` badput."""
        from .profiler import RecordEvent

        with RecordEvent("guardian/rollback"):
            return self._rollback_restore(
                manager, rb, scope=scope, program=program,
                executors=executors, readers=readers,
                shardings=shardings)

    def _rollback_restore(self, manager, rb, scope=None, program=None,
                          executors=None, readers=None, shardings=None):
        from .parallel.checkpoint import CheckpointCorruptError

        candidates = [s for s in manager.all_steps() if s <= rb.step]
        for s in reversed(candidates):
            # validate WITHOUT applying: a rejected artifact must leave
            # no side effects — no scope mutation, no
            # checkpoint_restored event, no save-cadence reseed — and
            # the no-clean-artifact abort below must leave the
            # pre-rollback state in place.  A structural mismatch out
            # of restore() still raises (configuration error, not a
            # fault).
            try:
                ts = manager.load(s)
            except CheckpointCorruptError as e:
                self._event({"event": "guardian_checkpoint_skipped",
                             "step": s, "reason": "corrupt",
                             "detail": str(e)})
                continue
            if not all(_finite(a) for a in ts.arrays.values()):
                self._counter("guardian/poisoned_checkpoints")
                self._event({"event": "guardian_checkpoint_skipped",
                             "step": s, "reason": "nonfinite_state"})
                continue
            restored = manager.restore(
                step=s, scope=scope, program=program,
                executors=executors, readers=readers,
                shardings=shardings, train_state=ts)
            # the measured replay debt of this recovery: steps between
            # the restored artifact and the failure, i.e. the work a
            # rollback re-executes.  autotune.tune_checkpoint_interval
            # prices the checkpoint cadence against exactly this.
            self.last_replay_steps = max(0, int(rb.step) - int(restored))
            self._event({"event": "guardian_rollback", "step": rb.step,
                         "reason": rb.reason, "restored_step": restored,
                         "replay_steps": self.last_replay_steps,
                         "rollbacks": self._rollbacks,
                         "quarantined": rb.quarantined})
            return restored
        raise GuardianAbortError(
            "guardian: rollback requested at step %d (%s) but no clean "
            "checkpoint exists at or below it" % (rb.step, rb.reason))

    def post_restore(self, rb, restored_step):
        """Reset detection state after a successful restore and return
        how many batches the reader should fast-forward: past the
        poisoned window (``failed - restored`` batches, ending just
        after the quarantined batch) when the failure implicates the
        data, else 0 (transient fault: the replay re-consumes the same
        batches and — by the exact-resume contract — reproduces the
        clean trajectory)."""
        self._pending.clear()
        self._history.clear()
        self._consecutive_skips = 0
        self._spike_run = 0
        self._plateau_armed = True
        if rb.quarantined:
            return max(0, rb.step + 1 - int(restored_step))
        return 0

    # -- watchdog escalation -------------------------------------------
    def _on_stall(self, diag):
        """monitor stall-listener: called from the watchdog thread at
        each stall firing.  Arms an abort after ``stall_escalations``
        consecutive firings with no completed step; the next observed
        step raises it (a thread-safe flag — raising from the watchdog
        thread could not unwind the training loop anyway, and a FULLY
        wedged device needs the external supervisor either way)."""
        self._stalls += 1
        if self._stalls >= self.stall_escalations > 0 \
                and self._stall_abort is None:
            self._counter("guardian/stall_escalations")
            self._event({"event": "guardian_stall_escalated",
                         "stalls": self._stalls,
                         "stalled_for_s": diag.get("stalled_for_s")})
            self._stall_abort = (
                "guardian: watchdog reported %d consecutive stall "
                "windows (%.0fs each) with no completed step — pipeline "
                "wedged" % (self._stalls,
                            diag.get("stall_seconds", 0.0)))

    # -- quarantine ----------------------------------------------------
    def _quarantine(self, step, feed, reason):
        """Persist the offending batch + its feed signature for repro;
        returns the quarantine record (path None when no dir is
        configured — the event still carries the signature)."""
        from . import monitor

        self.quarantined.append((int(step), reason))
        self._counter("guardian/quarantined_batches")
        # schema: feed_signature/feed_names for repro, provenance for
        # the first-offending-op record (filled in by _provenance after
        # the write; the sidecar JSON is rewritten in place then)
        rec = {"run_id": monitor.run_id(), "step": int(step),
               "reason": reason, "ts": time.time(), "path": None,
               "provenance": None}
        if feed is not None:
            names, vals = feed
            rec["feed_signature"] = [
                (n, list(np.shape(v)), str(np.asarray(v).dtype))
                for n, v in zip(names, vals)]
            if self.quarantine_dir:
                os.makedirs(self.quarantine_dir, exist_ok=True)
                base = os.path.join(
                    self.quarantine_dir,
                    "batch_%s_step%08d" % (monitor.run_id(), int(step)))
                # positional npz members + a name list in the sidecar
                # (same scheme as TrainState artifacts: npz member names
                # can't carry '/' etc. across numpy versions)
                with open(base + ".npz", "wb") as f:
                    np.savez(f, **{"arr_%d" % i: np.asarray(v)
                                   for i, v in enumerate(vals)})
                rec["feed_names"] = list(names)
                rec["path"] = base + ".npz"
                with open(base + ".json", "w") as f:
                    json.dump(rec, f)
        return rec

    # -- publication helpers -------------------------------------------
    @staticmethod
    def _counter(name):
        from . import monitor

        monitor.count(name)

    @staticmethod
    def _event(rec):
        from . import monitor
        from .cluster.runtime import local_context

        rec.setdefault("ts", time.time())
        # cluster runs stamp every guardian decision (rollback, skip,
        # stall escalation...) with the member identity + membership
        # epoch, so cluster-level post-mortems can join the per-host
        # JSONL logs; a no-op ({}) outside a cluster
        for k, v in local_context().items():
            rec.setdefault(k, v)
        monitor.log_event(rec)

    def stats(self):
        return {"skipped_steps": self.skipped_steps,
                "rollbacks": self._rollbacks,
                "quarantined": len(self.quarantined),
                "pending": len(self._pending),
                "window": list(self._history)}


# ---------------------------------------------------------------------------
# process-global installation (the executors' one-read hook)
# ---------------------------------------------------------------------------

_ACTIVE = None


def active():
    """The installed Guardian, or None — the executors' per-step gate
    (one module-global read when no guardian is installed)."""
    return _ACTIVE


def install(g):
    """Install ``g`` as the process guardian: both executors feed it
    every step, and it subscribes to watchdog stall firings.  Returns
    ``g``."""
    global _ACTIVE
    from . import monitor

    if _ACTIVE is not None and _ACTIVE is not g:
        monitor.remove_stall_listener(_ACTIVE._on_stall)
    _ACTIVE = g
    monitor.add_stall_listener(g._on_stall)
    return g


def uninstall():
    """Remove the installed guardian (its deferred observations are NOT
    flushed — call ``flush()`` first if the ladder should still fire)."""
    global _ACTIVE
    from . import monitor

    if _ACTIVE is not None:
        monitor.remove_stall_listener(_ACTIVE._on_stall)
    _ACTIVE = None


class installed:
    """Context manager: install ``g`` for the duration (no-op when
    ``g`` is None — the Trainer's disabled path)."""

    def __init__(self, g):
        self._g = g

    def __enter__(self):
        if self._g is not None:
            install(self._g)
        return self._g

    def __exit__(self, *exc):
        if self._g is not None and _ACTIVE is self._g:
            uninstall()
        return False
