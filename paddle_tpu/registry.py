"""Operator registry: shape/dtype inference + JAX compute + gradient makers.

Capability parity with the reference's op registry stack
(``paddle/fluid/framework/op_registry.h``, ``op_info.cc``,
``grad_op_desc_maker.h``, and OperatorWithKernel dispatch
``operator.h:315``), re-designed TPU-first:

* An op's *kernel* is a pure JAX function ``compute(ins, attrs, ctx)`` where
  ``ins`` maps input slot -> list of jax arrays.  There is no per-device
  kernel dispatch (OpKernelType, operator.cc:672): XLA owns placement and
  fusion; a single traceable function covers CPU/TPU, and Pallas kernels
  slot in as alternative compute bodies for hot ops (see ``ops/pallas/``).
* Gradients: instead of 300 hand-written grad kernels, the default grad maker
  wires a generic ``<type>_grad`` op whose kernel re-runs the forward under
  ``jax.vjp`` and applies the output cotangents.  Because the whole program
  is one traced jaxpr, XLA CSE merges the recomputed forward with the
  original — the recompute is free in the compiled HLO.  Ops that must not
  be re-executed (stateful randomness like dropout) register custom grad
  makers that consume saved forward outputs (e.g. the dropout mask), exactly
  the cases where the reference saves intermediates too.
* Shape inference (``infer``) runs at append time; it must handle -1 batch
  dims.  This is the build-time half of the reference's InferShape.
"""

import numpy as np

import jax
import jax.numpy as jnp

from .core import convert_dtype, dtype_is_floating
from .framework import grad_var_name

__all__ = [
    "OpDef",
    "register_op",
    "get_op_def",
    "infer_op",
    "compute_op",
    "make_grad_ops",
    "OPS",
]

OPS = {}


class ComputeContext:
    """Per-trace context handed to kernels: PRNG key material and flags."""

    def __init__(self, key=None, is_test=False, platform=None, mesh=None):
        self._key = key
        self.is_test = is_test
        self.amp = None  # AMPPolicy (contrib.mixed_precision) or None
        # the executing device's platform ("cpu"/"tpu"), threaded from the
        # executor's Place so Pallas call sites pick mosaic vs interpret
        self.platform = platform
        # the ParallelExecutor's device mesh (None single-device): ops with
        # mesh-aware lowerings (fused_attention -> ring attention over sp)
        # consult it at trace time
        self.mesh = mesh
        # {state var name: PartitionSpec} as the ParallelExecutor placed
        # the persistable state on the mesh — ops with sharded lowerings
        # (sparse embedding lookup/update over row-sharded tables) read
        # their operands' placement from here.  Empty single-device.
        self.state_specs = {}
        # the Operator currently being traced (set by compute_op): gives
        # kernels access to their input/output VAR NAMES so they can
        # consult state_specs
        self.op = None

    def rng_key(self, op_index):
        if self._key is None:
            raise RuntimeError(
                "op requires randomness but the executor provided no PRNG key"
            )
        return jax.random.fold_in(self._key, op_index)


class OpDef:
    def __init__(
        self,
        type,
        inputs,
        outputs,
        infer,
        compute,
        grad=None,
        no_grad_inputs=(),
        stateful_random=False,
        doc="",
    ):
        self.type = type
        self.input_slots = tuple(inputs)
        self.output_slots = tuple(outputs)
        self.infer = infer
        self.compute = compute
        # grad: None => not differentiable; "auto" => generic vjp;
        #       callable(op, block, no_grad_set) -> list of op-spec dicts
        self.grad = grad
        self.no_grad_inputs = frozenset(no_grad_inputs)
        self.stateful_random = stateful_random
        self.doc = doc


def register_op(
    type,
    inputs,
    outputs,
    infer,
    compute,
    grad="auto",
    no_grad_inputs=(),
    stateful_random=False,
    doc="",
):
    if type in OPS:
        raise ValueError("op type %r already registered" % type)
    OPS[type] = OpDef(
        type, inputs, outputs, infer, compute, grad, no_grad_inputs,
        stateful_random, doc,
    )
    return OPS[type]


def get_op_def(type):
    if type not in OPS:
        raise KeyError("op type %r is not registered" % type)
    return OPS[type]


def infer_op(op, block):
    """Run build-time shape/dtype inference for ``op`` in ``block``."""
    d = get_op_def(op.type)
    if d.infer is not None:
        d.infer(op, block)


def compute_op(op, env, ctx, op_index=0):
    """Execute one op inside a trace: read inputs from env, write outputs."""
    d = get_op_def(op.type)
    # empty names are "holes" (e.g. pruned grad slots): pass/collect None.
    # Out:: slots of grad ops are lenient — an optional forward output
    # (e.g. sequence_pool MaxIndex under "last") may never have been
    # produced.  A GRAD:: name is only lenient when its forward output is
    # itself absent; a missing gradient for a produced output is a real
    # wiring bug and must stay a loud KeyError, not silent zeros.
    ins = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if not n:
                vals.append(None)
            elif slot.startswith("Out::"):
                vals.append(env.get(n))
            elif slot.startswith("GRAD::"):
                fwd = n[: -len("@GRAD")] if n.endswith("@GRAD") else n
                vals.append(env.get(n) if fwd not in env else env[n])
            else:
                vals.append(env[n])
        ins[slot] = vals
    if ctx.amp is not None:
        ins = ctx.amp.cast_inputs(op.type, ins)
    # save/restore: region ops (pipeline_region, control flow) re-enter
    # compute_op for their body ops under the same ctx
    prev_op, ctx.op = ctx.op, op
    try:
        outs = d.compute(ins, op.attrs, ctx, op_index)
    finally:
        ctx.op = prev_op
    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for name, val in zip(names, vals):
            if name:
                env[name] = val
    return env


# --------------------------------------------------------------------------
# Generic gradient machinery
# --------------------------------------------------------------------------

GENERIC_GRAD_SUFFIX = "_grad"


def make_grad_ops(op, no_grad_set):
    """Return a list of grad-op specs for a forward op, or [] if none.

    A spec is a dict(type=..., inputs=..., outputs=..., attrs=...) with
    variable *names*.  Mirrors the reference's GradOpDescMaker protocol
    (grad_op_desc_maker.h) driven from backward.py.
    """
    d = get_op_def(op.type)
    if d.grad is None:
        return []
    if callable(d.grad):
        return d.grad(op, no_grad_set)
    if d.grad == "auto":
        return _auto_grad_maker(op, no_grad_set)
    raise ValueError("bad grad spec for op %r" % op.type)


def _auto_grad_maker(op, no_grad_set):
    """Default grad maker: one ``<type>_grad`` op taking all forward inputs,
    forward outputs, and output grads; producing input grads."""
    d = get_op_def(op.type)
    g_inputs = {}
    for slot, names in op.inputs.items():
        g_inputs[slot] = list(names)
    for slot, names in op.outputs.items():
        g_inputs["Out::" + slot] = list(names)
        g_inputs["GRAD::" + slot] = [grad_var_name(n) for n in names]
    g_outputs = {}
    any_grad = False
    for slot, names in op.inputs.items():
        if slot in d.no_grad_inputs:
            continue
        outs = []
        for n in names:
            if n in no_grad_set:
                outs.append("")  # hole: grad not needed
            else:
                outs.append(grad_var_name(n))
                any_grad = True
        g_outputs["GRAD::" + slot] = outs
    if not any_grad:
        return []
    attrs = dict(op.attrs)
    attrs["__fwd_type__"] = op.type
    return [
        dict(
            type=op.type + GENERIC_GRAD_SUFFIX,
            inputs=g_inputs,
            outputs=g_outputs,
            attrs=attrs,
        )
    ]


def _generic_grad_infer(gop, block):
    """Grad vars mirror the shape/dtype of their forward vars."""
    fwd_slots = [s for s in gop.inputs if not s.startswith(("Out::", "GRAD::"))]
    for slot in fwd_slots:
        out_slot = "GRAD::" + slot
        if out_slot not in gop.outputs:
            continue
        for fwd_name, g_name in zip(gop.inputs[slot], gop.outputs[out_slot]):
            if not g_name:
                continue
            fwd_var = block._find_var_recursive(fwd_name)
            if fwd_var is None:
                continue
            block.create_var(
                name=g_name,
                shape=fwd_var.shape,
                dtype=fwd_var.dtype,
                persistable=False,
            )


def _generic_grad_compute(ins, attrs, ctx, op_index):
    fwd_type = attrs["__fwd_type__"]
    fwd_def = get_op_def(fwd_type)
    fwd_attrs = {k: v for k, v in attrs.items()
                 if k not in ("__fwd_type__", "__fwd_op_index__")}
    # stateful-random forwards (nce sampling, dropout without its custom
    # grad) must re-draw the SAME randomness in the recompute: use the
    # forward op's trace index for the PRNG fold, not the grad op's
    op_index = attrs.get("__fwd_op_index__", op_index)

    primal_ins = {
        slot: vals
        for slot, vals in ins.items()
        if not slot.startswith(("Out::", "GRAD::"))
    }
    # differentiate only w.r.t. floating-point inputs
    diff_slots = []
    for slot, vals in primal_ins.items():
        if slot in fwd_def.no_grad_inputs:
            continue
        if all(dtype_is_floating(v.dtype) for v in vals) and vals:
            diff_slots.append(slot)

    def fwd_fn(diff_vals):
        full = dict(primal_ins)
        full.update(diff_vals)
        outs = fwd_def.compute(full, fwd_attrs, ctx, op_index)
        # canonicalize: slot -> list
        canon = {}
        for slot in fwd_def.output_slots:
            v = outs.get(slot)
            if v is None:
                continue
            canon[slot] = list(v) if isinstance(v, (list, tuple)) else [v]
        return canon

    diff_vals = {slot: primal_ins[slot] for slot in diff_slots}
    outs, vjp_fn = jax.vjp(fwd_fn, diff_vals)

    # build cotangents: use provided GRAD:: slots, zeros elsewhere
    cts = {}
    for slot, vals in outs.items():
        gslot = "GRAD::" + slot
        if gslot in ins and ins[gslot]:
            gvals = ins[gslot]
            # cotangents must match the recomputed forward's output dtype:
            # under the AMP policy a white-listed forward yields bf16 while
            # the incoming cotangent may be fp32 (or vice versa)
            cts[slot] = [
                g.astype(v.dtype) if g is not None else jnp.zeros_like(v)
                for g, v in zip(gvals, vals)
            ]
        else:
            cts[slot] = [jnp.zeros_like(v) for v in vals]

    (grads,) = vjp_fn(cts)

    result = {}
    for slot in diff_slots:
        result["GRAD::" + slot] = grads[slot]
    return result


class _GenericGradRegistrar:
    """Lazily register ``<type>_grad`` op defs the first time they appear."""

    @staticmethod
    def ensure(grad_type):
        if grad_type in OPS:
            return
        if not grad_type.endswith(GENERIC_GRAD_SUFFIX):
            raise KeyError(grad_type)
        fwd_type = grad_type[: -len(GENERIC_GRAD_SUFFIX)]
        if fwd_type not in OPS:
            raise KeyError(grad_type)
        OPS[grad_type] = OpDef(
            grad_type,
            inputs=(),
            outputs=(),
            infer=_generic_grad_infer,
            compute=_generic_grad_compute,
            grad=None,
            doc="auto-vjp gradient of %s" % fwd_type,
        )


_orig_get = get_op_def


def get_op_def(type):  # noqa: F811 — wraps to lazily add _grad defs
    if type not in OPS and type.endswith(GENERIC_GRAD_SUFFIX):
        try:
            _GenericGradRegistrar.ensure(type)
        except KeyError:
            pass
    if type not in OPS:
        raise KeyError("op type %r is not registered" % type)
    return OPS[type]


# --------------------------------------------------------------------------
# Shape-inference helpers shared by op definitions
# --------------------------------------------------------------------------

def set_output(op, block, slot, shape, dtype, lod_level=0):
    """Create/refresh the output var for slot (single-var slots)."""
    names = op.outputs.get(slot, [])
    for name in names:
        v = block._find_var_recursive(name)
        if v is None:
            v = block.create_var(name=name)
        v.shape = tuple(int(s) for s in shape) if shape is not None else None
        v.dtype = convert_dtype(dtype) if dtype is not None else None
        v.lod_level = lod_level


def in_var(op, block, slot, idx=0):
    names = op.inputs.get(slot, [])
    if not names:
        return None
    return block._find_var_recursive(names[idx])


def same_shape_infer(in_slot, out_slot):
    def infer(op, block):
        x = in_var(op, block, in_slot)
        set_output(op, block, out_slot, x.shape, x.dtype, x.lod_level)

    return infer


def broadcast_shapes(s1, s2):
    """Numpy-style broadcast of shapes with -1 (dynamic) dims propagated."""
    out = []
    for a, b in zip(reversed(s1), reversed(s2)):
        if a == -1 or b == -1:
            out.append(-1 if (a in (-1, 1) and b in (-1, 1)) else max(a, b))
        elif a == 1:
            out.append(b)
        elif b == 1 or a == b:
            out.append(a)
        else:
            raise ValueError("cannot broadcast %s with %s" % (s1, s2))
    longer = s1 if len(s1) > len(s2) else s2
    out.extend(reversed(longer[: abs(len(s1) - len(s2))]))
    return tuple(reversed(out))


def int_list(v, n):
    """Normalize a scalar-or-sequence attr (strides/paddings/ksize...) to a
    length-n list (shared by conv/pool ops and CNN layers)."""
    if isinstance(v, (list, tuple)):
        if len(v) != n:
            raise ValueError(
                "expected %d values, got %r" % (n, list(v))
            )
        return list(v)
    return [v] * n
