"""Program-graph IR: Program / Block / Operator / Variable / Parameter.

Capability parity with the reference's Python graph builder
(``python/paddle/fluid/framework.py`` — Variable:204, Operator:494, Block:920,
Program:1404, Parameter:1964) and the underlying ProgramDesc protobuf IR
(``paddle/fluid/framework/framework.proto:42-183``), re-designed TPU-first:

* There is no protobuf / C++ OpDesc mirror.  The Python objects ARE the IR;
  the executor lowers a Program directly to a jaxpr by tracing the registered
  JAX compute function of every op in order, then jit-compiles the whole
  program once (XLA fuses across op boundaries — the program is one HLO
  module, the TPU analog of whole-graph compilation named in the north star).
* Shape/dtype inference runs eagerly at ``append_op`` time through the op
  registry (the reference runs InferShape both at build time from Python and
  again inside OperatorWithKernel::RunImpl; with static shapes + XLA we only
  need the build-time pass).
* Blocks still exist — control-flow ops (while/cond, see
  ``layers/control_flow.py``) own sub-blocks which lower to ``lax.scan`` /
  ``lax.cond`` / ``lax.while_loop`` so everything stays inside one jit.
* Programs serialize to a plain JSON-able dict (``Program.to_dict`` /
  ``Program.from_dict``) which replaces ProgramDesc serialization for
  save/load_inference_model parity.
"""

import collections
import contextlib
import copy
import json

import numpy as np

from . import core, unique_name
from .core import VarType, convert_dtype

__all__ = [
    "Program",
    "Block",
    "Operator",
    "Variable",
    "Parameter",
    "default_startup_program",
    "default_main_program",
    "program_guard",
    "name_scope",
    "grad_var_name",
    "GRAD_VAR_SUFFIX",
]

GRAD_VAR_SUFFIX = "@GRAD"


def grad_var_name(var_name):
    """Name of the gradient variable of ``var_name`` (reference
    framework.py:grad_var_name / framework.cc GradVarName)."""
    return var_name + GRAD_VAR_SUFFIX


_name_scope_stack = []


@contextlib.contextmanager
def name_scope(prefix=None):
    """Name scoping for profiling/visualization (reference framework.py:80)."""
    _name_scope_stack.append(prefix or "")
    try:
        yield
    finally:
        _name_scope_stack.pop()


def _full_name_scope():
    return "/".join([s for s in _name_scope_stack if s])


class Variable:
    """A typed symbol in a Block (reference framework.py:204).

    Concrete storage lives in a ``Scope`` (name -> jax.Array); a Variable is
    only the compile-time description: shape (with -1 batch dims), dtype,
    persistable (parameters / optimizer state survive across executor runs),
    stop_gradient (backward pruning), lod_level (sequence nesting parity —
    packed representation, see ``paddle_tpu.sequence``).
    """

    def __init__(
        self,
        block,
        name=None,
        shape=None,
        dtype=None,
        type=VarType.DENSE_TENSOR,
        persistable=False,
        stop_gradient=False,
        is_data=False,
        lod_level=0,
        initializer=None,
        **kwargs,
    ):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = convert_dtype(dtype) if dtype is not None else None
        self.type = type
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.lod_level = lod_level
        self.initializer = initializer
        # op that produced this var most recently (set by append_op)
        self.op = None
        # name of the companion [batch] int32 length var for padded
        # sequences (the LoD replacement; see ops/sequence.py)
        self._seq_len_name = None

    # ---- properties used throughout layers --------------------------------
    @property
    def ndim(self):
        return len(self.shape) if self.shape is not None else None

    def astype_desc(self):
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": str(self.dtype) if self.dtype is not None else None,
            "type": self.type,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
            "lod_level": self.lod_level,
        }

    def __repr__(self):
        return "Variable(name=%s, shape=%s, dtype=%s%s)" % (
            self.name,
            self.shape,
            self.dtype,
            ", persistable" if self.persistable else "",
        )

    __str__ = __repr__


class Parameter(Variable):
    """A persistable, trainable Variable (reference framework.py:1964)."""

    def __init__(self, block, shape, dtype, **kwargs):
        if shape is None or dtype is None:
            raise ValueError("Parameter must have shape and dtype")
        for s in shape:
            if s <= 0:
                raise ValueError("each dim of Parameter must be > 0, got %s" % (shape,))
        kwargs.setdefault("persistable", True)
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)
        self.trainable = kwargs.get("trainable", True)
        self.optimize_attr = kwargs.get("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.get("regularizer", None)
        self.gradient_clip_attr = kwargs.get("gradient_clip_attr", None)
        self.do_model_average = kwargs.get("do_model_average", None)

    def __repr__(self):
        return "Parameter(name=%s, shape=%s, dtype=%s)" % (
            self.name,
            self.shape,
            self.dtype,
        )

    __str__ = __repr__


class Operator:
    """One node of the program graph (reference framework.py:494 /
    framework.proto:42 OpDesc).

    inputs/outputs map *slot* names to lists of variable names; attrs is a
    plain dict of JSON-able values.  Appending an operator immediately runs
    the registered shape/dtype inference so downstream layers can size
    parameters — the build-time half of the reference's InferShape.
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs = {}
        self.outputs = {}
        self.attrs = dict(attrs) if attrs else {}
        ns = _full_name_scope()
        if ns:
            self.attrs.setdefault("op_namescope", ns)

        def _canon(mapping):
            out = collections.OrderedDict()
            if not mapping:
                return out
            for slot, vs in mapping.items():
                if vs is None:
                    out[slot] = []
                    continue
                if not isinstance(vs, (list, tuple)):
                    vs = [vs]
                out[slot] = [v.name if isinstance(v, Variable) else v for v in vs]
            return out

        self.inputs = _canon(inputs)
        self.outputs = _canon(outputs)

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    @property
    def output_arg_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def has_attr(self, name):
        return name in self.attrs

    def to_dict(self):
        return {
            "type": self.type,
            "inputs": {k: list(v) for k, v in self.inputs.items()},
            "outputs": {k: list(v) for k, v in self.outputs.items()},
            "attrs": _jsonable_attrs(self.attrs),
        }

    def __repr__(self):
        return "{%s: (%s) -> (%s)}" % (
            self.type,
            ", ".join("%s=%s" % kv for kv in self.inputs.items()),
            ", ".join("%s=%s" % kv for kv in self.outputs.items()),
        )

    __str__ = __repr__


def _jsonable_attrs(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, np.dtype):
            v = str(v)
        elif isinstance(v, np.ndarray):
            v = v.tolist()
        elif isinstance(v, (np.integer,)):
            v = int(v)
        elif isinstance(v, (np.floating,)):
            v = float(v)
        out[k] = v
    return out


class Block:
    """An ordered list of Operators plus a symbol table of Variables
    (reference framework.py:920 / framework.proto:170 BlockDesc)."""

    def __init__(self, program, idx, parent_idx=-1, forward_block_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.forward_block_idx = forward_block_idx
        self.vars = collections.OrderedDict()  # name -> Variable
        self.ops = []

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    # ---- variable management ---------------------------------------------
    def create_var(self, **kwargs):
        name = kwargs.get("name", None)
        if name is not None and name in self.vars:
            return self.vars[name]
        var = Variable(self, **kwargs)
        self.vars[var.name] = var
        return var

    def create_parameter(self, **kwargs):
        # parameters always live in the top-level (global) block, like the
        # reference (framework.py Block.create_parameter promotes to global)
        global_block = self.program.global_block()
        param = Parameter(global_block, **kwargs)
        global_block.vars[param.name] = param
        return param

    def has_var(self, name):
        return name in self.vars

    def has_var_recursive(self, name):
        return self._find_var_recursive(name) is not None

    def var(self, name):
        v = self.vars.get(name)
        if v is None:
            raise ValueError("var %r does not exist in block %d" % (name, self.idx))
        return v

    def _find_var_recursive(self, name):
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def var_recursive(self, name):
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError("var %r not found in block %d or ancestors" % (name, self.idx))
        return v

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def rename_var(self, old_name, new_name):
        self.program._version += 1
        v = self.vars.pop(old_name)
        v.name = new_name
        self.vars[new_name] = v
        for op in self.ops:
            for slot, names in op.inputs.items():
                op.inputs[slot] = [new_name if n == old_name else n for n in names]
            for slot, names in op.outputs.items():
                op.outputs[slot] = [new_name if n == old_name else n for n in names]
        return v

    # ---- op management ----------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.append(op)
        self._infer_and_mark(op)
        return op

    def _prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(0, op)
        self._infer_and_mark(op)
        return op

    def insert_op(self, index, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(index, op)
        self._infer_and_mark(op)
        return op

    def _infer_and_mark(self, op):
        from .registry import infer_op  # local import to avoid cycle

        self.program._version += 1
        infer_op(op, self)
        # propagate the sequence-length companion (the padded-batch analog
        # of the reference's LoD propagation through ops): outputs inherit
        # the first input's length var unless they set their own
        seq_len = None
        for name in op.input_arg_names:
            v = self._find_var_recursive(name) if name else None
            if v is not None and getattr(v, "_seq_len_name", None):
                seq_len = v._seq_len_name
                break
        for name in op.output_arg_names:
            v = self._find_var_recursive(name)
            if v is not None:
                v.op = op
                if seq_len and not getattr(v, "_seq_len_name", None):
                    v._seq_len_name = seq_len

    def to_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "forward_block_idx": self.forward_block_idx,
            "vars": [v.astype_desc() | {"is_parameter": isinstance(v, Parameter)}
                     for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }

    def __repr__(self):
        lines = ["Block(%d):" % self.idx]
        for v in self.vars.values():
            lines.append("  " + repr(v))
        for op in self.ops:
            lines.append("  " + repr(op))
        return "\n".join(lines)

    __str__ = __repr__


class Program:
    """A whole trainable/inferable computation (reference framework.py:1404 /
    framework.proto:183).  Holds nested blocks; block 0 is global."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._op_role_stack = []
        # fingerprint cache for executor compile caching
        self._version = 0
        # trace-time mixed-precision policy (contrib.mixed_precision)
        self._amp_policy = None

    # ---- block management --------------------------------------------------
    def global_block(self):
        return self.blocks[0]

    def block(self, idx):
        return self.blocks[idx]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx=None, forward_block_idx=-1):
        new_idx = len(self.blocks)
        parent = self.current_block_idx if parent_idx is None else parent_idx
        self.blocks.append(Block(self, new_idx, parent_idx=parent,
                                 forward_block_idx=forward_block_idx))
        self.current_block_idx = new_idx
        return self.current_block()

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    # ---- parameters --------------------------------------------------------
    def all_parameters(self):
        return self.global_block().all_parameters()

    def list_vars(self):
        for blk in self.blocks:
            yield from blk.vars.values()

    # ---- cloning / pruning -------------------------------------------------
    def clone(self, for_test=False):
        """Deep-copy the program.  ``for_test=True`` rewrites training-only
        behavior (dropout/batch_norm switch to inference mode) like the
        reference's ``Program.clone(for_test=True)`` + inference_optimize."""
        p = copy.deepcopy(self)
        if for_test:
            for blk in p.blocks:
                for op in blk.ops:
                    if "is_test" in _TEST_MODE_OPS.get(op.type, ()):
                        op.attrs["is_test"] = True
        return p

    def prune_feed_fetch(self, feed_names, fetch_names):
        """Keep only ops needed to compute ``fetch_names`` from
        ``feed_names`` (reference prune.cc / Program._prune).  Returns a new
        Program over the same global block contents."""
        p = copy.deepcopy(self)
        blk = p.global_block()
        needed = set(fetch_names)
        kept = []
        for op in reversed(blk.ops):
            if set(op.output_arg_names) & needed:
                kept.append(op)
                for n in op.input_arg_names:
                    needed.add(n)
        blk.ops = list(reversed(kept))
        used = set()
        for op in blk.ops:
            used.update(op.input_arg_names)
            used.update(op.output_arg_names)
        used.update(feed_names)
        used.update(fetch_names)
        blk.vars = collections.OrderedDict(
            (n, v) for n, v in blk.vars.items() if n in used
        )
        return p

    # ---- serialization -----------------------------------------------------
    def to_dict(self):
        return {
            "version": 1,
            "random_seed": self.random_seed,
            "blocks": [b.to_dict() for b in self.blocks],
        }

    def to_json(self):
        return json.dumps(self.to_dict())

    @staticmethod
    def from_dict(d):
        p = Program()
        p.random_seed = d.get("random_seed", 0)
        p.blocks = []
        for bd in d["blocks"]:
            blk = Block(p, bd["idx"], bd.get("parent_idx", -1),
                        bd.get("forward_block_idx", -1))
            for vd in bd["vars"]:
                cls = Parameter if vd.get("is_parameter") else Variable
                kwargs = dict(
                    name=vd["name"],
                    shape=vd["shape"],
                    dtype=vd["dtype"],
                    type=vd.get("type", VarType.DENSE_TENSOR),
                    persistable=vd.get("persistable", False),
                    stop_gradient=vd.get("stop_gradient", False),
                    is_data=vd.get("is_data", False),
                    lod_level=vd.get("lod_level", 0),
                )
                v = cls(blk, **kwargs) if cls is Variable else cls(
                    blk, kwargs.pop("shape"), kwargs.pop("dtype"), **kwargs)
                blk.vars[v.name] = v
            for od in bd["ops"]:
                op = Operator(blk, od["type"], od["inputs"], od["outputs"], od["attrs"])
                blk.ops.append(op)
            p.blocks.append(blk)
        p.current_block_idx = 0
        return p

    @staticmethod
    def from_json(s):
        return Program.from_dict(json.loads(s))

    def fingerprint(self):
        """Stable hash for executor compile caching."""
        return hash(self.to_json())

    def __repr__(self):
        return "\n".join(repr(b) for b in self.blocks)

    __str__ = __repr__


# ops whose attrs flip in clone(for_test=True)
_TEST_MODE_OPS = {
    "dropout": ("is_test",),
    "batch_norm": ("is_test",),
    # test mode consumes the TRAINED running scale instead of updating it
    "fake_quantize_range_abs_max": ("is_test",),
}


# --------------------------------------------------------------------------
# default program singletons (reference framework.py:2048-2160)
# --------------------------------------------------------------------------
_main_program_ = Program()
_startup_program_ = Program()


def default_startup_program():
    return _startup_program_


def default_main_program():
    return _main_program_


def switch_main_program(program):
    global _main_program_
    prev = _main_program_
    _main_program_ = program
    return prev


def switch_startup_program(program):
    global _startup_program_
    prev = _startup_program_
    _startup_program_ = program
    return prev


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """Route subsequent layer calls into the given programs
    (reference framework.py:program_guard)."""
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)
