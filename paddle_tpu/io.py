"""Checkpoint / model persistence.

Parity: reference ``python/paddle/fluid/io.py`` (save/load_vars:89/295,
save/load_params:204/417, save/load_persistables:252/464,
save/load_inference_model:544/669) and the save_op/load_op tensor format —
TPU-native: tensors serialize as ``.npy`` files (one per var, like the
reference's one-file-per-var save_op) or a single combined ``.npz``
(save_combine_op parity); programs serialize to JSON (``__model__``).
Sharded/async checkpointing for the mesh runtime lives in
``paddle_tpu.parallel.checkpoint`` (orbax-style).
"""

import json
import os

import numpy as np

from .framework import Parameter, Program, default_main_program
from .scope import global_scope

__all__ = [
    "save_vars", "save_params", "save_persistables",
    "load_vars", "load_params", "load_persistables",
    "save_inference_model", "load_inference_model",
    "save_checkpoint", "load_checkpoint", "clean_checkpoint",
    "save_train_program", "load_train_program",
]


def _is_parameter(var):
    return isinstance(var, Parameter)


def _is_persistable(var):
    return var.persistable


def _npz_path(dirname, filename):
    # np.savez appends ".npz" itself; normalize so save and load agree
    if not filename.endswith(".npz"):
        filename += ".npz"
    return os.path.join(dirname, filename)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """Save scope values of selected program vars (reference io.py:89)."""
    if main_program is None:
        main_program = default_main_program()
    if vars is None:
        vars = [
            v for v in main_program.list_vars()
            if predicate is None or predicate(v)
        ]
    os.makedirs(dirname, exist_ok=True)
    scope = global_scope()
    if filename is not None:
        arrays = {}
        for v in vars:
            val = scope.find_var(v.name)
            if val is None:
                continue
            arrays[v.name] = np.asarray(val)
        np.savez(_npz_path(dirname, filename), **arrays)
        return
    for v in vars:
        val = scope.find_var(v.name)
        if val is None:
            continue
        np.save(os.path.join(dirname, v.name + ".npy"), np.asarray(val))


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_parameter,
              filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    """Params + optimizer accumulators + LR etc (reference io.py:252)."""
    save_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    if main_program is None:
        main_program = default_main_program()
    if vars is None:
        vars = [
            v for v in main_program.list_vars()
            if predicate is None or predicate(v)
        ]
    scope = global_scope()
    if filename is not None:
        with np.load(_npz_path(dirname, filename)) as data:
            for v in vars:
                if v.name in data:
                    scope.set_var(v.name, data[v.name])
        return
    for v in vars:
        path = os.path.join(dirname, v.name + ".npy")
        if os.path.exists(path):
            scope.set_var(v.name, np.load(path))


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_parameter,
              filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def save_inference_model(
    dirname, feeded_var_names, target_vars, executor, main_program=None,
    model_filename=None, params_filename=None, export_for_deployment=True,
):
    """Prune to the inference subgraph + save program & params
    (reference io.py:544).  The program is written as JSON ``__model__``."""
    if main_program is None:
        main_program = default_main_program()
    fetch_names = [v.name for v in target_vars]
    pruned = main_program.clone(for_test=True).prune_feed_fetch(
        feeded_var_names, fetch_names
    )
    os.makedirs(dirname, exist_ok=True)
    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "w") as f:
        json.dump({
            "program": pruned.to_dict(),
            "feed_names": list(feeded_var_names),
            "fetch_names": fetch_names,
        }, f)
    save_persistables(executor, dirname, pruned, filename=params_filename)
    return fetch_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    """Returns (program, feed_names, fetch_vars) (reference io.py:669)."""
    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path) as f:
        payload = json.load(f)
    program = Program.from_dict(payload["program"])
    load_persistables(executor, dirname, program, filename=params_filename)
    fetch_vars = [
        program.global_block().var(n) for n in payload["fetch_names"]
    ]
    return program, payload["feed_names"], fetch_vars


def save_train_program(dirname, main_program=None, startup_program=None,
                       loss_name=None, feed_names=None):
    """Serialize a FULL training program (forward + backward + optimizer
    ops) plus its startup program so training can run with no python
    graph build — the reference's train-without-python capability
    (``paddle/fluid/train/demo/demo_trainer.cc:1`` loads ProgramDescs
    and drives the C++ executor; here the JSON ProgramDesc analog +
    ``tools/train_from_program.py`` / ``load_train_program``)."""
    from .framework import default_startup_program

    if main_program is None:
        main_program = default_main_program()
    if startup_program is None:
        startup_program = default_startup_program()
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, "__train_program__"), "w") as f:
        json.dump({
            "main": main_program.to_dict(),
            "startup": startup_program.to_dict(),
            "loss_name": loss_name,
            "feed_names": list(feed_names or []),
        }, f)


def load_train_program(dirname):
    """Returns (main_program, startup_program, loss_name, feed_names).
    ``loss_name`` falls back to the first ``mean`` op's output when not
    recorded — the discovery rule of the reference demo trainer."""
    with open(os.path.join(dirname, "__train_program__")) as f:
        payload = json.load(f)
    main = Program.from_dict(payload["main"])
    startup = Program.from_dict(payload["startup"])
    loss_name = payload.get("loss_name")
    if not loss_name:
        for op in main.global_block().ops:
            if op.type == "mean":
                loss_name = op.outputs["Out"][0]
                break
    feed_names = payload.get("feed_names") or [
        name for name, v in main.global_block().vars.items()
        if getattr(v, "is_data", False)
    ]
    return main, startup, loss_name, feed_names


# ---- trainer-level checkpoints (reference io.py save_checkpoint family) ---

def save_checkpoint(executor, checkpoint_dir, trainer_id=0, main_program=None,
                    serial=None, max_num_checkpoints=3):
    """``serial=None`` auto-increments past the latest existing serial
    (reference io.py save_checkpoint: serial = latest + 1)."""
    if serial is None:
        serial = get_latest_checkpoint_serial(checkpoint_dir) + 1
    d = os.path.join(checkpoint_dir, "checkpoint_%d" % serial,
                     "trainer_%d" % trainer_id)
    save_persistables(executor, d, main_program, filename="persistables.npz")
    # prune old serials
    existing = sorted(
        int(n.split("_")[1]) for n in os.listdir(checkpoint_dir)
        if n.startswith("checkpoint_")
    )
    import shutil

    while len(existing) > max_num_checkpoints:
        victim = existing.pop(0)
        shutil.rmtree(os.path.join(checkpoint_dir, "checkpoint_%d" % victim),
                      ignore_errors=True)
    return d


def get_latest_checkpoint_serial(checkpoint_dir):
    if not os.path.isdir(checkpoint_dir):
        return -1
    serials = [
        int(n.split("_")[1]) for n in os.listdir(checkpoint_dir)
        if n.startswith("checkpoint_")
    ]
    return max(serials) if serials else -1


def load_checkpoint(executor, checkpoint_dir, trainer_id=0,
                    main_program=None, serial=None):
    if serial is None:
        serial = get_latest_checkpoint_serial(checkpoint_dir)
    if serial < 0:
        return False
    d = os.path.join(checkpoint_dir, "checkpoint_%d" % serial,
                     "trainer_%d" % trainer_id)
    load_persistables(executor, d, main_program, filename="persistables.npz")
    return True


def clean_checkpoint(checkpoint_dir, delete_dir=False):
    import shutil

    if not os.path.isdir(checkpoint_dir):
        return
    for n in os.listdir(checkpoint_dir):
        if n.startswith("checkpoint_"):
            shutil.rmtree(os.path.join(checkpoint_dir, n),
                          ignore_errors=True)
    if delete_dir and not os.listdir(checkpoint_dir):
        os.rmdir(checkpoint_dir)
