"""memory_optimize / release_memory (reference
``transpiler/memory_optimization_transpiler.py``: liveness-based var
reuse rewriting var names in the program).

TPU redesign: XLA's buffer assignment performs the same liveness
analysis on the fused HLO module, and the Executor donates state buffers
(in-place updates).  Rewriting the Program would at best duplicate and
at worst fight the compiler, so these are audited no-ops that return the
would-be savings for observability.
"""

import numpy as np

from ..framework import default_main_program

__all__ = ["memory_optimize", "release_memory"]


def memory_optimize(input_program=None, skip_opt_set=None, print_log=False,
                    level=0):
    """No-op on TPU (XLA owns buffer reuse); returns an estimate of the
    non-persistable temporary footprint the compiler will recycle."""
    program = input_program or default_main_program()
    skip = set(skip_opt_set or ())
    total = 0
    for v in program.list_vars():
        if v.persistable or v.name in skip or not v.shape:
            continue
        # dynamic (batch) dims count as 1: the estimate is per-sample
        dims = [d for d in v.shape if d is not None and d > 0]
        if not dims:
            continue
        total += int(np.prod(dims)) * 4
    if print_log:
        print("memory_optimize: ~%d bytes of temporaries left to XLA "
              "buffer reuse (no program rewrite on TPU)" % total)
    return total


def release_memory(input_program=None, skip_opt_set=None):
    """No-op: temporaries die inside the jitted step (no GC to trigger)."""
    return 0
