"""DistributeTranspiler: the sharding-plan rewriter.

Parity: reference ``transpiler/distribute_transpiler.py:144,237`` — there
it slices each param/grad into blocks (``slice_variable:79``), rewrites
the trainer program with send/recv ops and generates a pserver program
of optimize sub-blocks.  TPU-first redesign: parameters never leave the
mesh, so "transpiling" means deciding *where each tensor lives*:

* large params (numel >= min_block_size, the reference's slicing
  threshold) are sharded over the dp axis (ZeRO-style, the kReduce
  analog of pserver-sharded optimizer state);
* ``is_distributed`` embedding tables row-shard over ep/dp
  (the sharded lookup-table path);
* everything else is replicated.

``transpile()`` returns the plan; ``get_trainer_program()`` returns the
original program (nothing to rewrite — GSPMD inserts the collectives),
and ``get_pserver_program()`` raises: there is no server role.
"""

import numpy as np

from jax.sharding import PartitionSpec as P

from ..framework import default_main_program
from ..parallel.mesh import AXIS_DP, AXIS_EP
from ..parallel.strategy import BuildStrategy

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "slice_variable"]


def slice_variable(var_list, slice_count, min_block_size=8192):
    """Partition each variable into up to ``slice_count`` blocks
    (reference ``transpiler/distribute_transpiler.py:79 slice_variable``
    — there the blocks are pserver shards; here they are the ZeRO
    dp-rank shards the kReduce strategy assigns, so the same accounting
    answers "which rank owns which slice of optimizer state").

    Returns ``[(name, block_id, block_numel)]``.  Variables under
    ``min_block_size`` stay whole (one block); split counts never exceed
    the first-dimension extent, and blocks differ by at most one
    first-dim row — the even-split rule GSPMD sharding actually applies.
    """
    blocks = []
    for var in var_list:
        shape = tuple(var.shape or ())
        numel = int(np.prod(shape)) if shape else 1
        if numel < min_block_size or not shape or shape[0] <= 1 \
                or slice_count <= 1:
            blocks.append((var.name, 0, numel))
            continue
        k = min(slice_count, int(shape[0]))
        row = numel // int(shape[0])
        base, extra = divmod(int(shape[0]), k)
        for b in range(k):
            rows = base + (1 if b < extra else 0)
            blocks.append((var.name, b, rows * row))
    return blocks


class DistributeTranspilerConfig:
    """reference distribute_transpiler.py:125."""

    def __init__(self):
        self.slice_var_up = True
        # a ps_dispatcher class or its name: decides which shard owner
        # each sliced block lands on (see placement())
        self.split_method = "RoundRobin"
        self.min_block_size = 8192         # reference's slicing threshold


class DistributeTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._program = None
        self._plan = None
        self._placement = None

    # ------------------------------------------------------------------
    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, mesh=None):
        """Build the sharding plan.  ``pservers``/``sync_mode`` are taken
        for API parity; async pserver SGD has no TPU analog (every update
        is a synchronous mesh-wide step) and pserver endpoints are
        subsumed by the mesh."""
        if not sync_mode:
            raise NotImplementedError(
                "async pserver SGD has no TPU analog: updates are "
                "synchronous mesh-wide steps (SURVEY.md §2.4)")
        # validate the dispatcher BEFORE any state lands on self, so a
        # failed transpile leaves the object cleanly un-transpiled
        from . import ps_dispatcher
        method = self.config.split_method
        if isinstance(method, str):
            method = getattr(ps_dispatcher, method, None)
        if not (isinstance(method, type) and
                issubclass(method, ps_dispatcher.PSDispatcher)):
            raise ValueError(
                "split_method must be a PSDispatcher subclass or its "
                "name, got %r" % (self.config.split_method,))
        self._program = program or default_main_program()
        self.trainer_id = trainer_id
        self.trainers = trainers
        self._mesh = mesh

        from ..ops.selected_rows import sparse_lookup_tables
        dist_tables = set(sparse_lookup_tables(self._program,
                                               "is_distributed"))

        plan = {}
        for p in self._program.all_parameters():
            shape = tuple(p.shape or ())
            numel = int(np.prod(shape)) if shape else 0
            if p.name in dist_tables:
                plan[p.name] = ("table", P(AXIS_EP))
            elif self.config.slice_var_up and shape and \
                    numel >= self.config.min_block_size:
                plan[p.name] = ("sliced", P(AXIS_DP))
            else:
                plan[p.name] = ("replicated", P())
        self._plan = plan

        # block -> shard-owner placement via the configured dispatcher
        # (reference ps_dispatcher.py: block -> pserver endpoint).  The
        # owners are the pserver endpoints when given (parity surface)
        # or the dp ranks of the plan otherwise.
        owners = [e.strip() for e in (pservers or "").split(",")
                  if e.strip()]
        if not owners:
            owners = ["dp:%d" % r for r in range(max(1, int(trainers)))]
        dispatcher = method(owners)
        sliced = [p for p in self._program.all_parameters()
                  if plan[p.name][0] == "sliced"]
        whole = [p for p in self._program.all_parameters()
                 if plan[p.name][0] != "sliced"]
        blocks = slice_variable(sliced, len(owners),
                                self.config.min_block_size) + \
            [(p.name, 0, int(np.prod(tuple(p.shape or ()) or (1,))))
             for p in whole]
        keys = ["%s.block%d" % (name, bid) for name, bid, _ in blocks]
        self._placement = dict(zip(keys, dispatcher.dispatch(keys)))
        return self

    def placement(self):
        """{``name.blockN``: owner} — which shard owner each param block
        lands on, per ``config.split_method`` (the reference's
        param→pserver endpoint map, inspectable like its transpiler
        tests inspect generated programs)."""
        if self._placement is None:
            raise RuntimeError("call transpile() first")
        return dict(self._placement)

    # ------------------------------------------------------------------
    def sharding_plan(self):
        """{param name: (kind, PartitionSpec)} — inspectable, like the
        reference's transpiler tests inspect generated programs."""
        if self._plan is None:
            raise RuntimeError("call transpile() first")
        return dict(self._plan)

    def build_strategy(self, mesh):
        """A BuildStrategy whose param_sharding_fn applies the plan,
        degrading to replication when a dim doesn't divide the mesh."""
        if self._plan is None:
            raise RuntimeError("call transpile() first")
        axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        plan = self._plan

        def fn(name, shape):
            kind_spec = plan.get(name)
            if kind_spec is None:
                return None
            _, spec = kind_spec
            entries = tuple(spec)
            if not entries:
                return P()
            # substitute dp for axes this mesh lacks FIRST, then check
            # divisibility against the axes actually used — an
            # indivisible dim degrades to replication, never to an
            # invalid spec
            fixed = tuple(
                (a if a in axis_sizes else AXIS_DP) if a else None
                for a in entries)
            for dim, axis in zip(shape, fixed):
                if axis is None:
                    continue
                size = axis_sizes.get(axis, 1)
                if size > 1 and (dim <= 0 or dim % size != 0):
                    return P()
            return P(*fixed)

        bs = BuildStrategy()
        bs.reduce_strategy = BuildStrategy.ReduceStrategy.Reduce
        bs.param_sharding_fn = fn
        return bs

    # ------------------------------------------------------------------
    def get_trainer_program(self):
        """The program is NOT rewritten: GSPMD inserts the collectives
        the reference expressed as send/recv ops."""
        if self._program is None:
            raise RuntimeError("call transpile() first")
        return self._program

    def get_pserver_program(self, endpoint):
        raise RuntimeError(
            "there is no parameter-server role on the TPU runtime: "
            "parameters live sharded on the mesh (use build_strategy(mesh) "
            "with a ParallelExecutor; multi-host joins via "
            "parallel.distributed.init_distributed)")

    get_pserver_programs = get_pserver_program

    def get_startup_program(self, endpoint=None, pserver_program=None):
        raise RuntimeError(
            "no pserver startup program exists: run the normal startup "
            "program on every host (deterministic seeded init gives "
            "identical parameters)")
