"""Program-level fusion passes — the ir::Graph pass analog.

The reference rewrites graphs with C++ IR passes
(``paddle/fluid/framework/ir/graph.h``, and later releases ship a
``conv_bn_fuse_pass``); here a pass is a function over a ``Program``
rewriting its op list before ``append_backward``/``minimize`` runs, so
gradients are derived from the rewritten ops.

``fuse_conv_bn`` decomposes train-mode ``batch_norm`` ops and absorbs
eligible 1x1 convolutions into ``bn_act_conv2d`` fused ops
(``ops/fused_conv_bn.py``):

    conv2d(1x1) -> batch_norm -> relu -> conv2d(1x1) -> batch_norm ...

becomes

    bn_act_conv2d(+stats) -> stats_finalize -> bn_update_stats
                          -> bn_act_conv2d(normalize+relu prologue, +stats)

Each activation is then touched the minimum number of HBM passes: conv
outputs' statistics accumulate in the producing kernel's epilogue
(``stats_finalize`` is [C] arithmetic), and the normalize+relu runs in
the consuming kernel's prologue instead of materializing a normalized
copy.  BN semantics (running-stat momentum updates, SavedMean/
SavedVariance outputs, the three-term backward) are preserved — the
backward emerges from the decomposed graph's chain rule.

The pass refuses to rewrite when ``FLAGS_bn_two_pass`` is set: the
fused stats are one-pass by construction, and the flag's contract is
exact two-pass variance.
"""

from ..framework import Operator
from ..registry import infer_op, int_list

__all__ = ["fuse_conv_bn"]


def _is_conv1x1_s1(op, block):
    if op.type != "conv2d":
        return False
    if (op.attrs.get("groups", 1) or 1) != 1:
        return False
    strides = int_list(op.attrs.get("strides", 1), 2)
    pads = int_list(op.attrs.get("paddings", 0), 2)
    dils = int_list(op.attrs.get("dilations", 1), 2)
    if strides != [1, 1] or pads != [0, 0] or dils != [1, 1]:
        return False
    w = block._find_var_recursive(op.inputs["Filter"][0])
    x = block._find_var_recursive(op.inputs["Input"][0])
    if w is None or x is None or len(w.shape) != 4 or len(x.shape) != 4:
        return False
    return w.shape[2] == 1 and w.shape[3] == 1


def _is_train_bn(op, block):
    if op.type != "batch_norm":
        return False
    if op.attrs.get("is_test", False) or op.attrs.get("use_global_stats",
                                                      False):
        return False
    # NCHW programs and convert_to_nhwc-rewritten trunks both fuse; the
    # decomposed/fused ops carry the layout through their attrs
    if op.attrs.get("data_layout", "NCHW") not in ("NCHW", "NHWC"):
        return False
    x = block._find_var_recursive(op.inputs["X"][0])
    return x is not None and x.shape is not None and len(x.shape) == 4


def fuse_conv_bn(program):
    """Rewrite the global block in place; returns the number of
    batch_norm ops decomposed.  Must run BEFORE append_backward /
    optimizer.minimize (grad ops are derived from the rewritten
    program)."""
    from ..flags import flag

    if flag("bn_two_pass"):
        return 0

    block = program.global_block()
    ops = block.ops

    consumers = {}
    producer = {}
    for i, op in enumerate(ops):
        for name in op.input_arg_names:
            if name:
                consumers.setdefault(name, []).append(i)
        for name in op.output_arg_names:
            if name:
                producer[name] = i

    bn_idx = [i for i, op in enumerate(ops) if _is_train_bn(op, block)]
    if not bn_idx:
        return 0

    # --- plan -------------------------------------------------------------
    # consumer fusion: bn.Y [-> relu R] -> conv2d(1x1 s1); every link must
    # be the single consumer of its var
    absorbed_relu = set()    # relu op indices folded into a fused op
    absorbed_conv = {}       # conv op index -> (bn index, act)
    for i in bn_idx:
        bn = ops[i]
        y = bn.outputs["Y"][0]
        cons = consumers.get(y, [])
        act = ""
        tail = y
        j = cons[0] if len(cons) == 1 else -1
        if j >= 0 and ops[j].type == "relu":
            act = "relu"
            tail = ops[j].outputs["Out"][0]
            tcons = consumers.get(tail, [])
            k = tcons[0] if len(tcons) == 1 else -1
        else:
            k = j
        if k >= 0 and _is_conv1x1_s1(ops[k], block) \
                and ops[k].inputs["Input"][0] == tail \
                and ops[k].attrs.get("data_format", "NCHW") == \
                bn.attrs.get("data_layout", "NCHW"):
            if act == "relu":
                absorbed_relu.add(j)
            absorbed_conv[k] = (i, act)

    # producer-stats fusion: a 1x1 conv whose output is consumed ONLY by a
    # train-mode bn's X emits sum/sumsq from its kernel epilogue
    stats_conv = set()       # conv op indices that must emit stats
    bn_stats_src = {}        # bn index -> conv op index
    stats_consumer_bn = {}   # conv op index -> bn index consuming stats
    for i in bn_idx:
        x = ops[i].inputs["X"][0]
        p = producer.get(x)
        if p is not None and _is_conv1x1_s1(ops[p], block) \
                and consumers.get(x, []) == [i] \
                and ops[p].attrs.get("data_format", "NCHW") == \
                ops[i].attrs.get("data_layout", "NCHW"):
            stats_conv.add(p)
            bn_stats_src[i] = p
            stats_consumer_bn[p] = i

    # --- rebuild ----------------------------------------------------------
    def stat_names(conv_op):
        z = conv_op.outputs["Output"][0]
        return z + "@BNSUM", z + "@BNSUMSQ"

    def make_op(type, inputs, outputs, attrs):
        op = Operator(block, type=type, inputs=inputs, outputs=outputs,
                      attrs=attrs)
        infer_op(op, block)
        return op

    def emit_fused_conv(conv_i, new_ops):
        conv = ops[conv_i]
        with_stats = conv_i in stats_conv
        fmt = conv.attrs.get("data_format", "NCHW")
        # stat outputs always get real (dead when unused) names — an
        # empty-string output would register a phantom "" block var
        sum_n, sumsq_n = stat_names(conv)
        if conv_i in absorbed_conv:
            b_i, act = absorbed_conv[conv_i]
            bn = ops[b_i]
            inputs = {"X": list(bn.inputs["X"]),
                      "Filter": list(conv.inputs["Filter"]),
                      "BatchMean": list(bn.outputs["SavedMean"]),
                      "BatchVar": list(bn.outputs["SavedVariance"]),
                      "Scale": list(bn.inputs["Scale"]),
                      "Bias": list(bn.inputs["Bias"])}
            attrs = {"apply_bn": True, "act": act,
                     "with_stats": with_stats, "data_format": fmt,
                     "epsilon": bn.attrs.get("epsilon", 1e-5)}
        else:
            inputs = {"X": list(conv.inputs["Input"]),
                      "Filter": list(conv.inputs["Filter"])}
            attrs = {"apply_bn": False, "act": "",
                     "with_stats": with_stats, "data_format": fmt,
                     "epsilon": 1e-5}
        if with_stats:
            # the consumer bn's running mean shifts the fused sum/sumsq
            # accumulation (same cancellation guard as ops/norm.py's
            # shifted one-pass variance)
            consumer_bn = ops[stats_consumer_bn[conv_i]]
            inputs["StatsShift"] = list(consumer_bn.inputs["Mean"])
        new_ops.append(make_op(
            "bn_act_conv2d", inputs,
            {"Out": list(conv.outputs["Output"]),
             "SumOut": [sum_n], "SumSqOut": [sumsq_n]},
            attrs))

    new_ops = []
    fused = 0
    for i, op in enumerate(ops):
        # absorbed relu ops are RE-EMITTED (not skipped): their output
        # var may be fetched or read elsewhere; they read the bn_apply'd
        # Y and are dead code XLA eliminates when nothing consumes them
        if i in absorbed_conv or i in stats_conv:
            emit_fused_conv(i, new_ops)
            continue
        if i in bn_idx:
            bn = op
            layout = bn.attrs.get("data_layout", "NCHW")
            x_n = bn.inputs["X"][0]
            saved_mean = bn.outputs["SavedMean"][0]
            saved_var = bn.outputs["SavedVariance"][0]
            src = bn_stats_src.get(i)
            if src is not None:
                sum_n, sumsq_n = stat_names(ops[src])
                new_ops.append(make_op(
                    "stats_finalize",
                    {"Sum": [sum_n], "SumSq": [sumsq_n],
                     "CountFrom": [x_n],
                     "Shift": list(bn.inputs["Mean"])},
                    {"BatchMean": [saved_mean], "BatchVar": [saved_var]},
                    {"data_layout": layout}))
            else:
                new_ops.append(make_op(
                    "batch_stats",
                    {"X": [x_n], "Shift": list(bn.inputs["Mean"])},
                    {"BatchMean": [saved_mean], "BatchVar": [saved_var]},
                    {"data_layout": layout}))
            new_ops.append(make_op(
                "bn_update_stats",
                {"Mean": list(bn.inputs["Mean"]),
                 "Variance": list(bn.inputs["Variance"]),
                 "BatchMean": [saved_mean], "BatchVar": [saved_var]},
                {"MeanOut": list(bn.outputs["MeanOut"]),
                 "VarianceOut": list(bn.outputs["VarianceOut"])},
                {"momentum": bn.attrs.get("momentum", 0.9)}))
            # Y is always re-emitted via bn_apply: un-absorbed consumers
            # (residual adds, 3x3 convs, user fetches) read it, and when
            # every consumer was absorbed the op is dead code XLA
            # eliminates inside the one-jaxpr step
            y = bn.outputs["Y"][0]
            new_ops.append(make_op(
                "bn_apply",
                {"X": [x_n], "BatchMean": [saved_mean],
                 "BatchVar": [saved_var],
                 "Scale": list(bn.inputs["Scale"]),
                 "Bias": list(bn.inputs["Bias"])},
                {"Y": [y]},
                {"epsilon": bn.attrs.get("epsilon", 1e-5), "act": "",
                 "data_layout": layout}))
            fused += 1
            continue
        new_ops.append(op)
    block.ops = new_ops
    program._version += 1
    return fused
