"""Parameter-placement dispatchers (reference
``python/paddle/fluid/transpiler/ps_dispatcher.py:1``: RoundRobin /
HashName decide which pserver endpoint owns each sliced param block).

TPU-first role: there is no server process — the "endpoints" are the
shard owners of the ZeRO/kReduce plan (dp ranks, or literal endpoint
strings passed for API parity), and the dispatcher decides which owner
each ``slice_variable`` block lands on.  ``DistributeTranspiler``
consults ``config.split_method`` and exposes the result as
``placement()`` for transpiler-inspection tests.

``HashName`` hashes with crc32, not the builtin ``hash``: Python 3
salts string hashes per process, which would scatter the same program's
params differently on every trainer — a silent divergence the reference
(Python 2 era) never had to consider.
"""

import zlib

__all__ = ["PSDispatcher", "RoundRobin", "HashName"]


class PSDispatcher(object):
    """Base: holds the endpoint list; subclasses implement dispatch()."""

    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        """Map each var/block in ``varlist`` to an endpoint; returns a
        list of endpoints aligned with ``varlist``."""
        raise NotImplementedError("use RoundRobin or HashName")


class RoundRobin(PSDispatcher):
    """Cycle through endpoints in order (reference ps_dispatcher.py
    RoundRobin) — balanced block counts regardless of names."""

    def dispatch(self, varlist):
        out = []
        for _ in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out


class HashName(PSDispatcher):
    """Stable name-hash placement (reference ps_dispatcher.py HashName):
    the same var name always lands on the same endpoint, so a var can be
    located without a directory — at the cost of balance."""

    def _hash_block(self, name):
        return zlib.crc32(str(name).encode("utf-8")) % len(self._eps)

    def dispatch(self, varlist):
        return [self._eps[self._hash_block(getattr(v, "name", v))]
                for v in varlist]
