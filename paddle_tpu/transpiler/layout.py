"""Whole-trunk NHWC layout pass — the data-layout-transform analog.

The reference transforms tensor layouts at kernel boundaries when a
kernel wants a different layout than its input carries
(``paddle/fluid/framework/data_layout_transform.cc:1``, and the cuDNN
conv kernels' layout negotiation in
``paddle/fluid/operators/conv_cudnn_op.cu.cc:1``).  On TPU the
motivation is different — XLA's layout assignment already normalizes a
pure conv trunk (measured: NCHW == NHWC end-to-end, PERF.md r4) — but
*custom kernels* (the Pallas fused conv+BN family) tile as [M=B*H*W, C]
row-major, which is exactly flattened NHWC: under an NCHW program every
fused-op boundary materializes an NCHW<->NHWC transpose (measured 2.4x
regression, PERF.md), under an NHWC program none do.

``convert_to_nhwc`` rewrites the global block in place so the conv
trunk runs feature-last:

* ``conv2d``/``depthwise_conv2d`` become ``data_format=NHWC`` ops; ONE
  transpose is inserted where a trunk enters (the fed NCHW image);
  filters stay OIHW in the program (checkpoint/API parity — the conv
  kernel transposes the small weight tensor internally).
* ``batch_norm`` (``data_layout``), ``pool2d`` (``data_format``),
  unary activations/dropout/cast/scale, and trunk-trunk elementwise
  ops propagate the layout without touching bytes.
* Every other consumer of a trunk var gets an inserted NHWC->NCHW
  boundary transpose (the fc head's global-pool input is [B,1,1,C] vs
  [B,C,1,1] — byte-identical, XLA folds the transpose to a bitcast).

Var NAMES are preserved; only shape metadata flips to NHWC — fetching
an interior trunk var therefore yields NHWC data, the documented
contract of opting into the pass (the reference's transformed interior
is equally layout-rewritten).  Run BEFORE ``fuse_conv_bn`` (which
understands both layouts) and BEFORE ``append_backward``/``minimize``
so gradients derive from the rewritten program.
"""

from ..framework import Operator
from ..registry import infer_op

__all__ = ["convert_to_nhwc"]

# ops that pass layout through untouched (same-shape unary families)
_UNARY_PASS = {
    "relu", "relu6", "sigmoid", "tanh", "leaky_relu", "elu", "softplus",
    "softsign", "sqrt", "abs", "square", "exp", "swish", "hard_sigmoid",
    "brelu", "soft_relu", "pow", "stanh", "thresholded_relu", "dropout",
    "scale", "cast",
}

_EW_PASS = {
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
}


def _is_4d(block, name):
    v = block._find_var_recursive(name)
    return v is not None and v.shape is not None and len(v.shape) == 4


def _is_rank1(block, name):
    v = block._find_var_recursive(name)
    return v is not None and v.shape is not None and len(v.shape) == 1


def convert_to_nhwc(program):
    """Rewrite the global block's conv trunk to NHWC in place; returns
    the number of convolutions converted."""
    block = program.global_block()
    ops = block.ops
    new_ops = []
    nhwc = set()          # var names currently carrying NHWC data
    entry_cache = {}      # NCHW var -> its @NHWC transposed alias
    exit_cache = {}       # NHWC var -> its @NCHW transposed alias
    converted = 0

    def emit_transpose(src, dst, perm):
        op = Operator(block, type="transpose", inputs={"X": [src]},
                      outputs={"Out": [dst]}, attrs={"axis": perm})
        infer_op(op, block)
        new_ops.append(op)

    def to_nhwc(name):
        if name not in entry_cache:
            alias = name + "@NHWC"
            emit_transpose(name, alias, [0, 2, 3, 1])
            nhwc.add(alias)
            entry_cache[name] = alias
        return entry_cache[name]

    def to_nchw(name):
        if name not in exit_cache:
            alias = name + "@NCHW"
            emit_transpose(name, alias, [0, 3, 1, 2])
            exit_cache[name] = alias
        return exit_cache[name]

    for op in ops:
        t = op.type
        if t in ("conv2d", "depthwise_conv2d") \
                and op.attrs.get("data_format", "NCHW") == "NCHW" \
                and _is_4d(block, op.inputs["Input"][0]):
            x = op.inputs["Input"][0]
            if x not in nhwc:
                op.inputs["Input"] = [to_nhwc(x)]
            op.attrs["data_format"] = "NHWC"
            nhwc.add(op.outputs["Output"][0])
            infer_op(op, block)
            new_ops.append(op)
            converted += 1
            continue
        if t == "batch_norm" and op.inputs["X"][0] in nhwc:
            op.attrs["data_layout"] = "NHWC"
            nhwc.add(op.outputs["Y"][0])
            infer_op(op, block)
            new_ops.append(op)
            continue
        if t == "pool2d" and op.inputs["X"][0] in nhwc:
            op.attrs["data_format"] = "NHWC"
            nhwc.add(op.outputs["Out"][0])
            infer_op(op, block)
            new_ops.append(op)
            continue
        if t in _UNARY_PASS and op.inputs.get("X") \
                and op.inputs["X"][0] in nhwc:
            for names in op.outputs.values():
                nhwc.update(n for n in names if n)
            infer_op(op, block)
            new_ops.append(op)
            continue
        if t in _EW_PASS and op.inputs.get("X") and op.inputs.get("Y"):
            x, y = op.inputs["X"][0], op.inputs["Y"][0]
            if x in nhwc or y in nhwc:
                if x in nhwc and y in nhwc:
                    pass
                elif x in nhwc and _is_4d(block, y):
                    op.inputs["Y"] = [to_nhwc(y)]
                elif y in nhwc and _is_4d(block, x):
                    op.inputs["X"] = [to_nhwc(x)]
                elif x in nhwc and op.attrs.get("axis", -1) == 1 \
                        and _is_rank1(block, y):
                    # per-channel RANK-1 vector broadcast: C moved to
                    # the last axis, broadcasting's default (-1)
                    # alignment; higher-rank Y (e.g. [C,1,1]) would
                    # mis-align against (H,W,C) and falls through to
                    # the boundary path below
                    op.attrs["axis"] = -1
                else:
                    # un-convertible operand mix: leave the trunk here
                    op.inputs["X"] = [to_nchw(x) if x in nhwc else x]
                    op.inputs["Y"] = [to_nchw(y) if y in nhwc else y]
                    infer_op(op, block)
                    new_ops.append(op)
                    continue
                nhwc.add(op.outputs["Out"][0])
                infer_op(op, block)
                new_ops.append(op)
                continue
        # generic boundary: any other consumer reads NCHW
        changed = False
        for slot, names in op.inputs.items():
            if any(n in nhwc for n in names):
                op.inputs[slot] = [to_nchw(n) if n in nhwc else n
                                   for n in names]
                changed = True
        if changed:
            infer_op(op, block)
        new_ops.append(op)

    block.ops = new_ops
    program._version += 1
    return converted
