"""Debug-lowered program variant for NaN provenance (ISSUE 20).

The executors run a whole program as ONE jit-compiled module, so when a
step produces a non-finite value the step boundary is the observable
granularity — ``check_nan_inf`` can name the first bad *fetch*, not the
op that made it.  The reference framework's interpreter checks every
op's outputs inline (``operator.cc:717`` under its nan/inf debug flag);
this module recovers that granularity off the hot path: the same op
walk ``executor.trace_program`` traces is *interpreted eagerly* — each
op's compute function runs to a concrete value, its float outputs are
isfinite-tested in topological (program) order, and the walk stops at
the FIRST offending op.

Used by ``monitor.health.nan_provenance`` on the guardian quarantine /
``check_nan_inf`` raise paths: one replay of one already-quarantined
batch, never per step.  The replay is a pure function of (feed, scope
state, PRNG key), so it is deterministic — replaying a quarantined
batch reproduces the identical provenance (test-enforced).
"""

import jax
import jax.numpy as jnp

from .. import registry
from ..registry import ComputeContext

__all__ = ["first_nonfinite_op"]


def _nonfinite(v):
    """True iff ``v`` is a floating array holding any non-finite
    element (bf16/f8 included — jnp.isfinite has lowerings numpy
    lacks)."""
    dt = getattr(v, "dtype", None)
    if dt is None or not jnp.issubdtype(dt, jnp.inexact):
        return False
    return not bool(jnp.isfinite(v).all())


def first_nonfinite_op(program, feed, scope, key=None, platform=None,
                       classify=None):
    """Interpret ``program``'s global block op by op with concrete
    values and return the FIRST op whose output is non-finite:

    ``{"op_index", "op_type", "out_var", "layer", "in_vars"}``

    — or None when every output stays finite (the corruption was
    host-side, not produced by the graph).  ``feed`` is a name->array
    dict; unfed op inputs load from ``scope`` like the executor's state
    analysis; ``key`` is the step's PRNG key (same dropout masks as the
    quarantined step); ``classify`` maps state var names to layer-class
    labels (``monitor.health``'s probe plan) so the hit names which
    layer is sick.  ``in_vars`` lists the op's already-non-finite
    inputs: an op that merely *propagates* a poisoned input is
    distinguishable from the op that created it (the first hit, by
    construction, has no poisoned non-feed input upstream)."""
    block = program.global_block()
    env = {n: jnp.asarray(v) for n, v in feed.items()}
    if key is None:
        key = jax.random.key(program.random_seed or 0)
    ctx = ComputeContext(key=key, platform=platform)
    ctx.sequence_parallel = True
    ctx.pipeline_schedule = None
    ctx.pipeline_microbatches = None
    ctx.program = program
    ctx.amp = getattr(program, "_amp_policy", None)
    classify = classify or {}
    for i, op in enumerate(block.ops):
        for n in op.input_arg_names:
            if n and n not in env and scope is not None \
                    and scope.has_var(n):
                env[n] = jnp.asarray(scope.var(n))
        registry.compute_op(op, env, ctx, op_index=i)
        for out in op.output_arg_names:
            if not out or out not in env:
                continue
            if _nonfinite(env[out]):
                layer = None
                bad_ins = []
                for n in op.input_arg_names:
                    if layer is None and n in classify:
                        layer = classify[n]
                    if n in env and _nonfinite(env[n]):
                        bad_ins.append(n)
                return {"op_index": i, "op_type": op.type,
                        "out_var": out, "layer": layer,
                        "in_vars": bad_ins}
    return None
