"""Pass registry + builder — the reference's ir pass infrastructure.

Parity: ``paddle/fluid/framework/ir/pass.h`` (REGISTER_PASS + Pass::
Apply), ``pass_builder.cc`` (ordered pass pipelines selected by
BuildStrategy), and ``graph_pattern_detector.cc`` (subgraph matching
that fusion passes build on).

TPU-first redesign: a pass is a function over the *Program* (the single
IR of this stack — there is no separate ir::Graph because XLA owns the
post-lowering graph), registered by name so strategy objects and user
code can compose pipelines declaratively::

    from paddle_tpu.transpiler import PassBuilder
    pb = PassBuilder()
    pb.append_pass("fuse_conv_bn")
    pb.append_pass("graph_viz", path="/tmp/g.dot")
    pb.apply(program)

Passes mutate in place and return a pass-specific result (match count,
cloned program, dot text...).  ``find_chain`` is the pattern-matching
helper new fusion passes build on (the GraphPatternDetector analog for
straight-line producer->consumer chains, which is what every shipped
reference fusion pass matches).
"""

__all__ = ["register_pass", "get_pass", "list_passes", "apply_pass",
           "PassBuilder", "find_chain"]

_PASSES = {}


def register_pass(name, fn=None, doc=None):
    """Register ``fn`` as a program pass (decorator when fn is None).
    Reference REGISTER_PASS(name, class)."""
    def deco(f):
        if name in _PASSES:
            raise KeyError("pass %r already registered" % name)
        _PASSES[name] = f
        return f

    if fn is not None:
        if doc:
            fn.__doc__ = doc
        return deco(fn)
    return deco


def get_pass(name):
    if name not in _PASSES:
        raise KeyError("unknown pass %r (registered: %s)"
                       % (name, sorted(_PASSES)))
    return _PASSES[name]


def list_passes():
    return sorted(_PASSES)


def apply_pass(program, pass_or_fn, *args, **kwargs):
    """Run one pass (by registered name or as a raw function) over
    ``program``; returns the pass's result."""
    fn = get_pass(pass_or_fn) if isinstance(pass_or_fn, str) \
        else pass_or_fn
    return fn(program, *args, **kwargs)


class PassBuilder:
    """Ordered pass pipeline (reference pass_builder.cc: AppendPass/
    InsertPass/RemovePass then apply in order)."""

    def __init__(self):
        self._pipeline = []   # (name, kwargs)

    def append_pass(self, name, **kwargs):
        get_pass(name)  # fail fast on unknown names
        self._pipeline.append((name, kwargs))
        return self

    def insert_pass(self, idx, name, **kwargs):
        get_pass(name)
        self._pipeline.insert(idx, (name, kwargs))
        return self

    def remove_pass(self, idx):
        self._pipeline.pop(idx)
        return self

    def all_passes(self):
        return [n for n, _ in self._pipeline]

    def apply(self, program):
        """Apply the pipeline in order; returns {pass_name: result}
        (last invocation wins for a repeated pass; the full ordered
        [(name, result)] history is under "__history__").  A pass
        returning a new Program (e.g. inference_optimize) feeds that
        program to the passes after it; the final program is under
        "__program__"."""
        from ..framework import Program

        results = {}
        history = []
        current = program
        for name, kwargs in self._pipeline:
            r = apply_pass(current, name, **kwargs)
            results[name] = r
            history.append((name, r))
            if isinstance(r, Program):
                current = r
        results["__program__"] = current
        results["__history__"] = history
        return results


def find_chain(block, op_types):
    """Match straight-line chains ``op_types[0] -> ... -> op_types[-1]``
    where each op's first output feeds the next op's first data input
    and has no other consumer (the fusion-safety condition every
    reference fuse pass checks).  Returns a list of op-index tuples.

    The GraphPatternDetector analog for the chain shapes the shipped
    reference passes match (conv+bn, fc+act, seqconv+pool...).
    """
    ops = block.ops
    consumers = {}
    for i, op in enumerate(ops):
        for n in op.input_arg_names:
            if n:
                consumers.setdefault(n, []).append(i)

    def out0(i):
        for names in ops[i].outputs.values():
            if names:
                return names[0]
        return None

    chains = []
    for start, op in enumerate(ops):
        if op.type != op_types[0]:
            continue
        chain = [start]
        ok = True
        for want in op_types[1:]:
            prev = chain[-1]
            o = out0(prev)
            use = consumers.get(o, [])
            # sole consumer, of the wanted type, fed through an input
            if o is None or len(use) != 1 or ops[use[0]].type != want:
                ok = False
                break
            chain.append(use[0])
        if ok:
            chains.append(tuple(chain))
    return chains


# ---- built-in registrations ------------------------------------------------

def _register_builtins():
    from ..debugger import draw_block_graphviz
    from .fusion import fuse_conv_bn
    from .inference_transpiler import InferenceTranspiler
    from .memory_optimization_transpiler import memory_optimize

    register_pass("fuse_conv_bn", fuse_conv_bn)
    register_pass("memory_optimize", memory_optimize)

    @register_pass("inference_optimize")
    def _inference_optimize(program, place=None, scope=None):
        """clone(for_test) + frozen-BN folding; returns the NEW
        program (InferenceTranspiler as a pass)."""
        return InferenceTranspiler().transpile(program, place, scope)

    @register_pass("bfloat16")
    def _bfloat16(program, place=None, scope=None, fetch_targets=None):
        """contrib.float16's bf16 inference rewrite as a pass."""
        from ..contrib.float16 import Bfloat16Transpiler

        return Bfloat16Transpiler().transpile(
            program, place, scope=scope, fetch_targets=fetch_targets)

    @register_pass("graph_viz")
    def _graph_viz(program, path="./temp.dot", render=False):
        """Dump the program graph as graphviz dot (reference
        ir/graph_viz_pass.cc); returns the written path."""
        return draw_block_graphviz(program.global_block(), path=path,
                                   render=render)


_register_builtins()
