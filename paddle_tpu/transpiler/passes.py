"""Pass registry + builder — the reference's ir pass infrastructure.

Parity: ``paddle/fluid/framework/ir/pass.h`` (REGISTER_PASS + Pass::
Apply), ``pass_builder.cc`` (ordered pass pipelines selected by
BuildStrategy), and ``graph_pattern_detector.cc`` (subgraph matching
that fusion passes build on).

TPU-first redesign: a pass is a function over the *Program* (the single
IR of this stack — there is no separate ir::Graph because XLA owns the
post-lowering graph), registered by name so strategy objects and user
code can compose pipelines declaratively::

    from paddle_tpu.transpiler import PassBuilder
    pb = PassBuilder()
    pb.append_pass("fuse_conv_bn")
    pb.append_pass("graph_viz", path="/tmp/g.dot")
    pb.apply(program)

Passes mutate in place and return a pass-specific result (match count,
cloned program, dot text...).  ``find_chain`` is the pattern-matching
helper new fusion passes build on (the GraphPatternDetector analog for
straight-line producer->consumer chains, which is what every shipped
reference fusion pass matches).
"""

__all__ = ["register_pass", "get_pass", "list_passes", "apply_pass",
           "PassBuilder", "find_chain", "dead_var_eliminate",
           "const_fold"]

_PASSES = {}


def register_pass(name, fn=None, doc=None):
    """Register ``fn`` as a program pass (decorator when fn is None).
    Reference REGISTER_PASS(name, class)."""
    def deco(f):
        if name in _PASSES:
            raise KeyError("pass %r already registered" % name)
        _PASSES[name] = f
        return f

    if fn is not None:
        if doc:
            fn.__doc__ = doc
        return deco(fn)
    return deco


def get_pass(name):
    if name not in _PASSES:
        raise KeyError("unknown pass %r (registered: %s)"
                       % (name, sorted(_PASSES)))
    return _PASSES[name]


def list_passes():
    return sorted(_PASSES)


def apply_pass(program, pass_or_fn, *args, **kwargs):
    """Run one pass (by registered name or as a raw function) over
    ``program``; returns the pass's result."""
    fn = get_pass(pass_or_fn) if isinstance(pass_or_fn, str) \
        else pass_or_fn
    return fn(program, *args, **kwargs)


class PassBuilder:
    """Ordered pass pipeline (reference pass_builder.cc: AppendPass/
    InsertPass/RemovePass then apply in order)."""

    def __init__(self):
        self._pipeline = []   # (name, kwargs)

    def append_pass(self, name, **kwargs):
        get_pass(name)  # fail fast on unknown names
        self._pipeline.append((name, kwargs))
        return self

    def insert_pass(self, idx, name, **kwargs):
        get_pass(name)
        self._pipeline.insert(idx, (name, kwargs))
        return self

    def remove_pass(self, idx):
        self._pipeline.pop(idx)
        return self

    def all_passes(self):
        return [n for n, _ in self._pipeline]

    def apply(self, program):
        """Apply the pipeline in order; returns {pass_name: result}
        (last invocation wins for a repeated pass; the full ordered
        [(name, result)] history is under "__history__").  A pass
        returning a new Program (e.g. inference_optimize) feeds that
        program to the passes after it; the final program is under
        "__program__"."""
        from ..framework import Program

        results = {}
        history = []
        current = program
        for name, kwargs in self._pipeline:
            r = apply_pass(current, name, **kwargs)
            results[name] = r
            history.append((name, r))
            if isinstance(r, Program):
                current = r
        results["__program__"] = current
        results["__history__"] = history
        return results


def find_chain(block, op_types):
    """Match straight-line chains ``op_types[0] -> ... -> op_types[-1]``
    where each op's first output feeds the next op's first data input
    and has no other consumer (the fusion-safety condition every
    reference fuse pass checks).  Returns a list of op-index tuples.

    The GraphPatternDetector analog for the chain shapes the shipped
    reference passes match (conv+bn, fc+act, seqconv+pool...).
    """
    ops = block.ops
    consumers = {}
    for i, op in enumerate(ops):
        for n in op.input_arg_names:
            if n:
                consumers.setdefault(n, []).append(i)

    def out0(i):
        for names in ops[i].outputs.values():
            if names:
                return names[0]
        return None

    chains = []
    for start, op in enumerate(ops):
        if op.type != op_types[0]:
            continue
        chain = [start]
        ok = True
        for want in op_types[1:]:
            prev = chain[-1]
            o = out0(prev)
            use = consumers.get(o, [])
            # sole consumer, of the wanted type, fed through an input
            if o is None or len(use) != 1 or ops[use[0]].type != want:
                ok = False
                break
            chain.append(use[0])
        if ok:
            chains.append(tuple(chain))
    return chains


# ---- semantics-preserving cleanup passes (ROADMAP item 5) ------------------

def _has_sub_block(op):
    # control-flow ops (while/conditional_block/pipeline_region) read
    # vars through their sub-blocks; liveness must treat them as roots
    return "sub_block" in op.attrs


def dead_var_eliminate(program, fetch_names=None):
    """Remove ops and vars that cannot affect ``fetch_names`` or any
    persistable state (reference ``ir/graph.h`` dead-code passes /
    prune.cc, as an in-place cleanup pass).

    Live roots: the fetch set, every op writing a persistable var
    (optimizer updates, running stats), and every op owning a sub-block
    (control flow reads through it).  With ``fetch_names`` omitted the
    pass is conservative — every terminal output counts as live — so it
    only drops unreferenced symbol-table vars.  Returns
    ``{"ops_removed": n, "vars_removed": m}``."""
    block = program.global_block()
    ops = block.ops
    if fetch_names is None:
        consumed = set()
        for op in ops:
            consumed.update(op.input_arg_names)
        fetch = {n for op in ops for n in op.output_arg_names
                 if n and n not in consumed}
    else:
        fetch = {n for n in fetch_names if n}
    live = set(fetch)
    keep = [False] * len(ops)
    for i in range(len(ops) - 1, -1, -1):
        op = ops[i]
        root = _has_sub_block(op)
        if not root:
            for n in op.output_arg_names:
                v = block._find_var_recursive(n) if n else None
                if v is not None and v.persistable:
                    root = True
                    break
        if root or (set(op.output_arg_names) & live):
            keep[i] = True
            live.update(n for n in op.input_arg_names if n)
    new_ops = [op for i, op in enumerate(ops) if keep[i]]
    ops_removed = len(ops) - len(new_ops)
    block.ops = new_ops
    used = set(fetch)
    for op in new_ops:
        used.update(op.input_arg_names)
        used.update(op.output_arg_names)
    import collections

    before = len(block.vars)
    block.vars = collections.OrderedDict(
        (n, v) for n, v in block.vars.items()
        if n in used or v.persistable or v.is_data)
    vars_removed = before - len(block.vars)
    if ops_removed or vars_removed:
        program._version += 1
    return {"ops_removed": ops_removed, "vars_removed": vars_removed}


# ops safe to evaluate at pass time: pure, deterministic, attr-driven
# (no PRNG key, no scope state beyond their const inputs)
_FOLDABLE = {
    "fill_constant", "assign", "assign_value", "scale", "cast",
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "sum", "minus", "sign", "clip",
}


def const_fold(program, max_elements=65536):
    """Evaluate compile-time-constant op chains (rooted at
    ``fill_constant``/``assign_value``) once at pass time and replace
    each still-needed result with a single ``assign_value`` op
    (reference ``ir/constant_folding_pass.cc``).  Ops with persistable
    outputs are never folded — they participate in the executor's
    writeback contract — and neither are ops producing more than
    ``max_elements`` values (a folded constant lives as a Python list
    in the op attrs, hashed by every fingerprint and serialized into
    ``__model__``; a giant mask is cheaper as the fill_constant it
    already is).  In place; returns the number of ops folded away."""
    from ..registry import ComputeContext, get_op_def

    import jax.numpy as jnp
    import numpy as _np

    block = program.global_block()
    ctx = ComputeContext(key=None, is_test=True, platform="cpu")
    # a name written MORE THAN ONCE is never a constant: a later
    # non-folded writer would rebind it, and folding consumers against
    # the first write's value miscompiles (name-keyed map, no SSA)
    write_counts = {}
    for op in block.ops:
        for n in op.output_arg_names:
            if n:
                write_counts[n] = write_counts.get(n, 0) + 1
    rebound = {n for n, c in write_counts.items() if c > 1}
    known = {}
    folded = set()
    for i, op in enumerate(block.ops):
        if op.type not in _FOLDABLE:
            continue
        if any(n in rebound for n in op.output_arg_names):
            continue
        names = [n for ns in op.inputs.values() for n in ns if n]
        if any(n not in known for n in names):
            continue
        skip = False
        for n in op.output_arg_names:
            v = block._find_var_recursive(n) if n else None
            if v is not None and v.persistable:
                skip = True
            if v is not None and v.shape is not None:
                size = 1
                for s in v.shape:
                    size *= max(1, int(s))
                if size > int(max_elements):
                    skip = True
        if skip:
            continue
        ins = {slot: [known.get(n) if n else None for n in ns]
               for slot, ns in op.inputs.items()}
        try:
            outs = get_op_def(op.type).compute(ins, op.attrs, ctx, i)
        except Exception:  # noqa: BLE001 — an unfoldable corner stays
            continue       # in the program, correct either way
        for slot, onames in op.outputs.items():
            vals = outs.get(slot)
            if vals is None:
                continue
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            for nm, v in zip(onames, vals):
                if nm:
                    known[nm] = jnp.asarray(v)
        folded.add(i)
    if not folded:
        return 0
    # folded values still consumed by surviving ops (or terminal in the
    # program — a fetchable result) materialize as one assign_value
    all_consumed = set()
    needed = set()
    for i, op in enumerate(block.ops):
        all_consumed.update(op.input_arg_names)
        if i not in folded:
            needed.update(n for n in op.input_arg_names if n in known)
    for i in folded:
        for nm in block.ops[i].output_arg_names:
            if nm and nm not in all_consumed:
                needed.add(nm)      # terminal constant: keep fetchable
    from ..framework import Operator
    from ..registry import infer_op

    new_ops = []
    materialized = set()
    for i, op in enumerate(block.ops):
        if i not in folded:
            new_ops.append(op)
            continue
        for nm in op.output_arg_names:
            if nm in needed and nm not in materialized:
                v = _np.asarray(known[nm])
                a = Operator(
                    block, type="assign_value", inputs={},
                    outputs={"Out": [nm]},
                    attrs={"shape": [int(s) for s in v.shape],
                           "dtype": str(v.dtype),
                           "values": v.ravel().tolist()})
                infer_op(a, block)
                new_ops.append(a)
                materialized.add(nm)
    block.ops = new_ops
    program._version += 1
    return len(folded)


# ---- built-in registrations ------------------------------------------------

def _register_builtins():
    from ..debugger import draw_block_graphviz
    from .fusion import fuse_conv_bn
    from .inference_transpiler import InferenceTranspiler
    from .memory_optimization_transpiler import memory_optimize

    register_pass("fuse_conv_bn", fuse_conv_bn)
    register_pass("memory_optimize", memory_optimize)
    register_pass("dead_var_eliminate", dead_var_eliminate)
    register_pass("const_fold", const_fold)

    @register_pass("quantize_inference")
    def _quantize_inference(program, scope=None, mode="weight_only",
                            weight_bits=8):
        """int8 program rewrite (quantize_pass.quantize_inference):
        returns the NEW quantized program (chained by PassBuilder)."""
        from .quantize_pass import quantize_inference

        return quantize_inference(program, scope=scope, mode=mode,
                                  weight_bits=weight_bits)

    @register_pass("inference_optimize")
    def _inference_optimize(program, place=None, scope=None):
        """clone(for_test) + frozen-BN folding; returns the NEW
        program (InferenceTranspiler as a pass)."""
        return InferenceTranspiler().transpile(program, place, scope)

    @register_pass("bfloat16")
    def _bfloat16(program, place=None, scope=None, fetch_targets=None):
        """contrib.float16's bf16 inference rewrite as a pass."""
        from ..contrib.float16 import Bfloat16Transpiler

        return Bfloat16Transpiler().transpile(
            program, place, scope=scope, fetch_targets=fetch_targets)

    @register_pass("graph_viz")
    def _graph_viz(program, path="./temp.dot", render=False):
        """Dump the program graph as graphviz dot (reference
        ir/graph_viz_pass.cc); returns the written path."""
        return draw_block_graphviz(program.global_block(), path=path,
                                   render=render)


_register_builtins()
