"""InferenceTranspiler (reference ``transpiler/inference_transpiler.py``:
BN folding into conv/fc weights, conv+relu fusion for MKLDNN).

TPU redesign: XLA fuses conv+bias+BN+relu chains in the compiled module,
so the arithmetic rewrites are unnecessary; what remains semantically is
switching train-mode ops to inference (the clone(for_test) rewrite).
"""

__all__ = ["InferenceTranspiler"]


class InferenceTranspiler:
    def transpile(self, program, place=None, scope=None):
        """Return an inference-mode copy of ``program`` (dropout/BN to
        is_test); numeric fusion is left to XLA."""
        return program.clone(for_test=True)
