"""InferenceTranspiler (reference ``transpiler/inference_transpiler.py``:
BN folding into conv weights, conv+relu fusion for MKLDNN).

TPU semantics: XLA already fuses the normalize+relu ELEMENTWISE chain
into the compiled module, but an inference-mode batch_norm still costs a
full per-channel affine pass over the conv output every run.  Folding
the (frozen) BN statistics INTO the convolution weights removes the op
entirely — the same arithmetic rewrite the reference performs:

    W' = W * gamma / sqrt(var + eps)        (per output channel)
    b' = beta - mean * gamma / sqrt(var + eps)

Relu fusion stays with XLA (it is free there).
"""

import numpy as np

from ..framework import Operator, Program
from ..registry import infer_op
from ..scope import global_scope

__all__ = ["InferenceTranspiler"]


class InferenceTranspiler:
    def transpile(self, program, place=None, scope=None):
        """Return an inference-optimized COPY of ``program``: train-mode
        ops switch to is_test (``clone(for_test=True)``), then frozen
        batch_norm stats fold into preceding conv weights (new
        ``@BNFOLD`` parameter values written to ``scope``).  The input
        program is never mutated — use the return value."""
        if not isinstance(program, Program):
            raise TypeError("program should be a Program")
        scope = scope if scope is not None else global_scope()
        cloned = program.clone(for_test=True)
        self._fuse_batch_norm(cloned, scope)
        return cloned

    # ------------------------------------------------------------------
    def _fuse_batch_norm(self, program, scope):
        block = program.global_block()
        ops = block.ops
        consumers = {}
        producer = {}
        for i, op in enumerate(ops):
            for n in op.input_arg_names:
                if n:
                    consumers.setdefault(n, []).append(i)
            for n in op.output_arg_names:
                if n:
                    producer[n] = i

        folded = set()      # bn op indices folded away
        rewires = {}        # bn op index -> (conv_out, bias_name, y_name)
        for i, op in enumerate(ops):
            if op.type != "batch_norm":
                continue
            if not (op.attrs.get("is_test") or
                    op.attrs.get("use_global_stats")):
                continue
            x = op.inputs["X"][0]
            p = producer.get(x)
            if p is None or ops[p].type != "conv2d":
                continue
            if consumers.get(x, []) != [i]:
                continue   # conv output used elsewhere: keep the bn
            conv = ops[p]
            w_name = conv.inputs["Filter"][0]
            if not scope.has_var(w_name):
                continue   # parameters not materialized: nothing to fold
            gamma = np.asarray(scope.var(op.inputs["Scale"][0]),
                               dtype=np.float64)
            beta = np.asarray(scope.var(op.inputs["Bias"][0]),
                              dtype=np.float64)
            mean = np.asarray(scope.var(op.inputs["Mean"][0]),
                              dtype=np.float64)
            var = np.asarray(scope.var(op.inputs["Variance"][0]),
                             dtype=np.float64)
            eps = op.attrs.get("epsilon", 1e-5)
            w = np.asarray(scope.var(w_name))
            scale = gamma / np.sqrt(var + eps)          # [O]
            w_f = (w.astype(np.float64)
                   * scale[:, None, None, None]).astype(w.dtype)
            b_f = (beta - mean * scale).astype(w.dtype)

            # unique per BN (a SHARED filter followed by different BNs
            # must fold to different values)
            y_name = op.outputs["Y"][0]
            folded_w = "%s@BNFOLD@%s" % (w_name, y_name)
            folded_b = "%s@BNFOLD_BIAS@%s" % (w_name, y_name)
            wv = block._find_var_recursive(w_name)
            block.create_var(name=folded_w, shape=wv.shape, dtype=wv.dtype,
                             persistable=True)
            block.create_var(name=folded_b, shape=(w.shape[0],),
                             dtype=wv.dtype, persistable=True)
            scope.set_var(folded_w, w_f)
            scope.set_var(folded_b, b_f)
            conv.inputs["Filter"] = [folded_w]
            # the bn disappears; its Y is now conv_out + b_f (one
            # elementwise_add the consumer fuses), wired in the rebuild
            folded.add(i)
            rewires[i] = (x, folded_b, y_name)

        if not folded:
            return 0
        new_ops = []
        for i, op in enumerate(ops):
            if i in folded:
                conv_out, bias_name, y = rewires[i]
                add = Operator(block, type="elementwise_add",
                               inputs={"X": [conv_out], "Y": [bias_name]},
                               outputs={"Out": [y]},
                               attrs={"axis": 1})
                infer_op(add, block)
                new_ops.append(add)
                continue
            new_ops.append(op)
        block.ops = new_ops
        program._version += 1
        return len(folded)