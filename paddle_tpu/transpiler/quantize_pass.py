"""``quantize_inference`` — the int8 program-rewrite pass (ISSUE 14).

The QAT stack (``contrib.quantize``/``ops/quantize.py``) only
*simulates* int8: weights stay float and carry grid rounding error.
This pass makes inference programs **execute** int8: every matmul/mul
(FC) weight becomes an int8 persistable plus a per-output-channel
dequant-scale vector, and the op is rewritten to ``dequant_matmul``
(``ops/quantize.py``: Pallas fused kernel or XLA ``dot_general``
fallback, selected per shape through the autotune decision table).

Per the GSPMD philosophy (PAPERS.md), the rewrite is a program-level
annotation: the pass only changes what the program *says* — which
weights are int8, which grids apply — and the kernel layer does the
work.  Two modes:

* ``weight_only`` — weights int8, activations untouched (f32
  accumulate).  The safe default; the 4x weight-byte shrink is where
  serving throughput/$ comes from.
* ``dynamic`` — activations additionally quantize per batch (per-row
  abs-max grid) to int8 and the dot accumulates in int32.  When the
  program carries a trained QAT activation scale
  (``fake_quantize_range_abs_max`` running state), the pass consumes it
  as the static activation grid instead of re-measuring.

QAT calibration: a weight fed through a fake-quant op deploys on the
grid QAT trained against — the trained ``OutScale`` envelope (or the
identical recomputed abs-max for stateless ``abs_max`` weights) — and
the weight-side fake-quant op disappears from the rewritten program.

The int8 weights and scale vectors are *persistable scope vars*, so
``save_inference_model`` ships them (the pruned program no longer
references the float master weights — the artifact shrinks) and a cold
``load_inference_model``/serving-engine load runs quantized with no
re-calibration.
"""

import numpy as np

from ..framework import Operator
from ..registry import infer_op
from ..scope import global_scope

__all__ = ["quantize_inference", "QUANT_SUFFIX", "SCALE_SUFFIX"]

QUANT_SUFFIX = "@INT8"
SCALE_SUFFIX = "@INT8_SCALE"

_FAKE_QUANT_OPS = ("fake_quantize_abs_max", "fake_quantize_range_abs_max")
_MODES = ("weight_only", "dynamic")


def _trained_scale(op, scope):
    """The trained QAT calibration envelope of a fake-quant op, or None
    when no usable state exists (abs_max ops are stateless; a zero
    running scale means the state was never trained)."""
    if op is None or op.type != "fake_quantize_range_abs_max":
        return None
    names = op.inputs.get("InScale") or []
    if not names or not scope.has_var(names[0]):
        return None
    s = np.asarray(scope.var(names[0]), dtype=np.float64).ravel()
    if s.size == 0 or float(np.max(s)) <= 0:
        return None
    return s


def _floatish(var):
    return var.dtype is not None and "float" in str(var.dtype)


def quantize_inference(program, scope=None, mode="weight_only",
                       weight_bits=8, reuse_existing=False):
    """Return a NEW program with matmul/mul weights rewritten to int8
    ``dequant_matmul`` execution; ``scope`` gains the ``<w>@INT8`` /
    ``<w>@INT8_SCALE`` persistable values.  The input program is never
    mutated (pass-framework contract: a pass returning a Program feeds
    it to the passes after it).

    ``reuse_existing=True`` trusts ``@INT8``/``@INT8_SCALE`` values
    already in the scope instead of re-quantizing (the int8 grid is
    mode-independent): the shared-scope multi-program case —
    ``DecoderSpec.quantize`` rewrites three programs over one weight
    set — quantizes each weight once.  Leave it False when the fp
    masters may have changed since the values were written."""
    if mode not in _MODES:
        raise ValueError("quantize_inference mode must be one of %s, "
                         "got %r" % (_MODES, mode))
    scope = scope if scope is not None else global_scope()
    out = program.clone(for_test=True)
    block = out.global_block()
    rng_max = float((1 << (int(weight_bits) - 1)) - 1)

    producers = {}
    for op in block.ops:
        for nm in op.output_arg_names:
            if nm:
                producers[nm] = op

    converted = {}          # weight name -> (int8 name, scale name)
    info = {"mode": mode, "weight_bits": int(weight_bits), "weights": {}}
    new_ops = []
    for op in block.ops:
        if op.type not in ("mul", "matmul"):
            new_ops.append(op)
            continue
        if op.type == "matmul" and (op.attrs.get("transpose_X")
                                    or op.attrs.get("transpose_Y")
                                    or op.attrs.get("alpha", 1.0) != 1.0):
            new_ops.append(op)
            continue
        if op.type == "mul" and op.attrs.get("y_num_col_dims", 1) != 1:
            new_ops.append(op)
            continue
        x_name = op.inputs["X"][0]
        y_name = op.inputs["Y"][0]
        # unwrap a QAT weight fake-quant: its raw input is the weight,
        # its trained envelope the calibration
        wname, w_fq = y_name, None
        p = producers.get(y_name)
        if p is not None and p.type in _FAKE_QUANT_OPS:
            wname, w_fq = p.inputs["X"][0], p
        wvar = block._find_var_recursive(wname)
        if wvar is None or not wvar.persistable or not _floatish(wvar) \
                or not scope.has_var(wname):
            new_ops.append(op)
            continue
        w = np.asarray(scope.var(wname))
        if w.ndim != 2:
            new_ops.append(op)
            continue

        if wname not in converted:
            n_out = w.shape[1]
            qname = wname + QUANT_SUFFIX
            sname = wname + SCALE_SUFFIX
            if reuse_existing and scope.has_var(qname) \
                    and scope.has_var(sname) \
                    and np.asarray(scope.var(qname)).shape == \
                    tuple(w.shape):
                # shared-scope multi-program case: the values are
                # already there (mode-independent grid) — declare the
                # vars, skip the re-quantization
                calibration, q_size = "reused", int(np.asarray(w).size)
            else:
                w64 = np.asarray(w, np.float64)
                fq_scale = _trained_scale(w_fq, scope)
                if fq_scale is not None:
                    # the trained envelope IS the grid QAT optimized
                    # against (per-channel when trained per-channel;
                    # a scalar envelope broadcasts)
                    sw = fq_scale if fq_scale.size == n_out else np.full(
                        (n_out,), float(fq_scale.ravel()[0]), np.float64)
                    calibration = "qat_out_scale"
                else:
                    sw = np.abs(w64).max(axis=0)
                    calibration = "abs_max"
                sw = np.maximum(sw, 1e-12) / rng_max  # dequant multiplier
                q = np.clip(np.round(w64 / sw), -rng_max,
                            rng_max).astype(np.int8)
                scope.set_var(qname, q)
                scope.set_var(sname, sw.astype(np.float32))
                q_size = int(q.size)
            block.create_var(name=qname, shape=tuple(w.shape),
                             dtype="int8", persistable=True)
            block.create_var(name=sname, shape=(int(n_out),),
                             dtype="float32", persistable=True)
            converted[wname] = (qname, sname)
            info["weights"][wname] = {
                "int8": qname, "scale": sname,
                "calibration": calibration,
                "bytes_fp": int(np.asarray(w).size
                                * np.dtype(w.dtype).itemsize),
                "bytes_int8": q_size}
        qname, sname = converted[wname]

        # activation side: a trained QAT activation envelope feeds the
        # dynamic mode as a static grid (calibration consumed, not
        # re-measured); weight-only leaves activation fake-quants alone
        # (they are the numerics QAT trained)
        raw_x, xscale = x_name, None
        if mode == "dynamic":
            px = producers.get(x_name)
            if px is not None and px.type in _FAKE_QUANT_OPS:
                ts = _trained_scale(px, scope)
                if ts is not None:
                    raw_x = px.inputs["X"][0]
                    xscale = px.inputs["InScale"][0]
        xvar = block._find_var_recursive(raw_x)
        xnc = op.attrs.get("x_num_col_dims", 1) if op.type == "mul" \
            else max(1, len(xvar.shape) - 1)
        inputs = {"X": [raw_x], "QWeight": [qname], "Scale": [sname]}
        if xscale is not None:
            inputs["XScale"] = [xscale]
        nop = Operator(block, type="dequant_matmul", inputs=inputs,
                       outputs={"Out": list(op.outputs["Out"])},
                       attrs={"x_num_col_dims": xnc, "mode": mode,
                              "bit_length": int(weight_bits)})
        infer_op(nop, block)
        new_ops.append(nop)

    if not converted:
        block.ops = new_ops
        out._version += 1
        out._quantize_info = info
        return out

    # consumed fake-quant ops disappear: a weight-side (or bypassed
    # activation-side) fake-quant whose Out no longer feeds anything
    # else is dead
    consumed_by = {}
    for i, op in enumerate(new_ops):
        for nm in op.input_arg_names:
            if nm:
                consumed_by.setdefault(nm, set()).add(i)
    final_ops = []
    for i, op in enumerate(new_ops):
        if op.type in _FAKE_QUANT_OPS:
            users = set()
            for nm in op.outputs.get("Out", []):
                users |= consumed_by.get(nm, set())
            users.discard(i)
            if not users:
                continue
        final_ops.append(op)
    block.ops = final_ops
    out._version += 1
    out._quantize_info = info
    return out
