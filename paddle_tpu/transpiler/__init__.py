"""Program→program rewrites (reference ``python/paddle/fluid/transpiler/``).

On TPU most of the reference transpilers' work moved into the compiler:

* DistributeTranspiler → a *sharding plan* (mesh + BuildStrategy policy
  fns); there are no separate trainer/pserver programs to generate.
* memory_optimization_transpiler → XLA liveness analysis + buffer
  donation (Executor donates state buffers already); memory_optimize is
  kept as an API no-op that reports what XLA does instead.
* inference_transpiler → ``Program.clone(for_test=True)`` + XLA fusion
  (BN folding, conv+relu fusion happen in the compiler).
"""

from .distribute_transpiler import (  # noqa: F401
    DistributeTranspiler, DistributeTranspilerConfig)
from .ps_dispatcher import (  # noqa: F401
    PSDispatcher, RoundRobin, HashName)
from .memory_optimization_transpiler import (  # noqa: F401
    memory_optimize, release_memory)
from .inference_transpiler import InferenceTranspiler  # noqa: F401
from .fusion import fuse_conv_bn  # noqa: F401
from .layout import convert_to_nhwc  # noqa: F401
from .passes import (  # noqa: F401
    PassBuilder, apply_pass, const_fold, dead_var_eliminate, find_chain,
    get_pass, list_passes, register_pass)
from .quantize_pass import quantize_inference  # noqa: F401
from .nan_debug import first_nonfinite_op  # noqa: F401

__all__ = [
    "DistributeTranspiler", "DistributeTranspilerConfig",
    "PSDispatcher", "RoundRobin", "HashName",
    "memory_optimize", "release_memory", "InferenceTranspiler",
    "fuse_conv_bn", "convert_to_nhwc", "apply_pass", "register_pass",
    "get_pass",
    "list_passes", "PassBuilder", "find_chain",
    "dead_var_eliminate", "const_fold", "quantize_inference",
    "first_nonfinite_op",
]
