"""Arithmetic operators on LayerOutput (reference
python/paddle/trainer_config_helpers/layer_math.py:1).

The reference monkey-patches ``LayerOutput.__add__``/``__sub__``/
``__mul__`` to emit slope_intercept / addto / dotmul layers so v1
configs can write ``0.5 * layer + bias_layer``.  Here the same
operators are installed on the shared ``cfg.Layer`` handle (used by
both the v1 and v2 dialects), emitting the fluid-parity ops.
"""

from ..v2 import config as cfg

__all__ = []


def _scalar(x):
    return isinstance(x, (int, float))


def _add(self, other):
    from . import layers as tch
    if _scalar(other):
        return tch.slope_intercept_layer(self, intercept=float(other))
    return tch.addto_layer([self, other])


def _radd(self, other):
    return _add(self, other)


def _sub(self, other):
    from . import layers as tch
    if _scalar(other):
        return tch.slope_intercept_layer(self, intercept=-float(other))
    neg = tch.slope_intercept_layer(other, slope=-1.0)
    return tch.addto_layer([self, neg])


def _rsub(self, other):
    from . import layers as tch
    neg = tch.slope_intercept_layer(self, slope=-1.0)
    if _scalar(other):
        return tch.slope_intercept_layer(neg, intercept=float(other))
    return tch.addto_layer([neg, other])


def _mul(self, other):
    from . import layers as tch
    from .. import layers as fl
    if _scalar(other):
        return tch.slope_intercept_layer(self, slope=float(other))
    with cfg.build():
        var = fl.elementwise_mul(self.var, other.var)
    return cfg.Layer(var, v2_dim=self.v2_dim, parents=[self, other])


def _rmul(self, other):
    return _mul(self, other)


def _div(self, other):
    from . import layers as tch
    if not _scalar(other):
        raise TypeError("layer / layer is not part of the v1 layer math; "
                        "use layers.elementwise_div on the Variables")
    return tch.slope_intercept_layer(self, slope=1.0 / float(other))


def install_on(cls):
    """Install the operators on a LayerOutput-duck-typed class
    (cfg.Layer here; layers.MixedLayerType installs itself too so a
    context-manager-built mixed_layer supports layer math)."""
    cls.__add__ = _add
    cls.__radd__ = _radd
    cls.__sub__ = _sub
    cls.__rsub__ = _rsub
    cls.__mul__ = _mul
    cls.__rmul__ = _rmul
    cls.__truediv__ = _div


install_on(cfg.Layer)
