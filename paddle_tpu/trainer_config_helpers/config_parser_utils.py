"""Config parsing entry points (reference
python/paddle/trainer_config_helpers/config_parser_utils.py:1 +
python/paddle/trainer/config_parser.py parse_config).

In the v1 pipeline these ran a config file/function under the global
proto parser and returned ``ModelConfig``/``OptimizationConfig`` protos
for the trainer binary.  Here a network config function builds the
process-global Program pair (v2/config.py), and the "proto" is the
Program's JSON-dict serialization (framework.Program.to_dict — the
ProgramDesc analog, SURVEY §2.1); the optimizer config returns the
recorded ``TrainingSettings``.
"""

from ..v2 import config as cfg
from . import data_sources, optimizers

__all__ = ["parse_network_config", "parse_optimizer_config",
           "parse_trainer_config", "reset_parser"]


def reset_parser():
    """Fresh global state (reference config_parser_utils.reset_parser).
    Also resets the unique-name generator so re-parsing the identical
    config yields the identical serialized model (parameter names are
    the save/load keys — a drifting suffix would break re-parse +
    load-by-name workflows)."""
    from .. import unique_name
    cfg.reset()
    optimizers.reset_settings()
    data_sources.reset_data_sources()
    unique_name.switch()


class ParsedModel(object):
    """What parse_network_config returns: the live Programs plus the
    serialized model dict (the ModelConfig-proto analog)."""

    def __init__(self, graph):
        self.graph = graph
        self.program = graph.main
        self.startup_program = graph.startup
        self.input_layer_names = [l.name for l in graph.data_layers]
        out = getattr(graph, "output_layers", None) or []
        self.output_layer_names = [l.name for l in out]
        self.output_layers = list(out)

    def to_dict(self):
        return {
            "program": self.program.to_dict(),
            "startup_program": self.startup_program.to_dict(),
            "input_layer_names": self.input_layer_names,
            "output_layer_names": self.output_layer_names,
        }


def parse_network_config(network_conf, config_arg_str=""):
    """Run a v1 network config function and return the parsed model
    (reference config_parser_utils.parse_network_config).  The config
    function takes no arguments; ``config_arg_str`` is accepted for
    signature parity (v1 passed it through to the config's globals)."""
    reset_parser()
    network_conf()
    return ParsedModel(cfg.graph())


def parse_optimizer_config(optimizer_conf, config_arg_str=""):
    """Run a settings() config function and return the recorded
    TrainingSettings (reference parse_optimizer_config)."""
    optimizers.reset_settings()
    optimizer_conf()
    st = optimizers.current_settings()
    if st is None:
        raise ValueError("optimizer config did not call settings()")
    return st


def parse_trainer_config(config_fn, config_arg_str=""):
    """Run a full v1 trainer config (settings + data sources + network)
    and return (ParsedModel, TrainingSettings) — the TrainerConfig-proto
    analog."""
    reset_parser()
    config_fn()
    st = optimizers.current_settings()
    return ParsedModel(cfg.graph()), st
