"""v1 optimizer settings DSL (reference
python/paddle/trainer_config_helpers/optimizers.py:1).

In the v1 pipeline ``settings()`` mutated the global ``TrainerConfig``
proto that the ``paddle_trainer`` binary consumed.  Here it records a
``TrainingSettings`` object in module state; ``config_parser_utils.
parse_optimizer_config`` returns it, and ``to_v2()`` converts it to the
v2 optimizer object the (single) execution engine trains with — one
engine, three API dialects (fluid / v2 / v1 configs).
"""

from ..v2 import optimizer as v2_opt

__all__ = [
    "Optimizer", "BaseSGDOptimizer", "MomentumOptimizer", "AdamOptimizer",
    "AdamaxOptimizer", "AdaGradOptimizer", "DecayedAdaGradOptimizer",
    "AdaDeltaOptimizer", "RMSPropOptimizer", "L2Regularization",
    "L1Regularization", "ModelAverage", "GradientClippingThreshold",
    "settings", "current_settings", "reset_settings",
]


class Optimizer(object):
    """Base marker (reference optimizers.py:28)."""


class BaseSGDOptimizer(Optimizer):
    v2_class = None
    kwargs = {}

    def to_v2(self, **common):
        return self.v2_class(**dict(self.kwargs, **common))


class MomentumOptimizer(BaseSGDOptimizer):
    """reference optimizers.py:74; sparse=True selected the sparse
    momentum kernel in v1 — the SelectedRows path here is automatic."""

    v2_class = v2_opt.Momentum

    def __init__(self, momentum=None, sparse=False):
        self.kwargs = {"momentum": momentum if momentum is not None else 0.0}


class AdamOptimizer(BaseSGDOptimizer):
    v2_class = v2_opt.Adam

    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.kwargs = {"beta1": beta1, "beta2": beta2, "epsilon": epsilon}


class AdamaxOptimizer(BaseSGDOptimizer):
    v2_class = v2_opt.Adamax

    def __init__(self, beta1=0.9, beta2=0.999):
        self.kwargs = {"beta1": beta1, "beta2": beta2}


class AdaGradOptimizer(BaseSGDOptimizer):
    v2_class = v2_opt.AdaGrad

    def __init__(self):
        self.kwargs = {}


class DecayedAdaGradOptimizer(BaseSGDOptimizer):
    v2_class = v2_opt.DecayedAdaGrad

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.kwargs = {"rho": rho, "epsilon": epsilon}


class AdaDeltaOptimizer(BaseSGDOptimizer):
    v2_class = v2_opt.AdaDelta

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.kwargs = {"rho": rho, "epsilon": epsilon}


class RMSPropOptimizer(BaseSGDOptimizer):
    v2_class = v2_opt.RMSProp

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.kwargs = {"rho": rho, "epsilon": epsilon}


class L2Regularization(Optimizer):
    def __init__(self, rate):
        self.rate = rate


class L1Regularization(Optimizer):
    def __init__(self, rate):
        self.rate = rate


class ModelAverage(Optimizer):
    def __init__(self, average_window, max_average_window=None,
                 do_average_in_cpu=False):
        self.average_window = average_window
        self.max_average_window = max_average_window


class GradientClippingThreshold(Optimizer):
    def __init__(self, threshold):
        self.threshold = threshold


class TrainingSettings(object):
    """What ``settings()`` records: batch size, LR schedule, and the
    update rule (the v1 TrainerConfig's optimization section)."""

    def __init__(self, batch_size, learning_rate, learning_method,
                 regularization, gradient_clipping_threshold, model_average,
                 learning_rate_decay_a, learning_rate_decay_b,
                 learning_rate_schedule):
        self.batch_size = batch_size
        self.learning_rate = learning_rate
        self.learning_method = learning_method
        self.regularization = regularization
        self.gradient_clipping_threshold = gradient_clipping_threshold
        self.model_average = model_average
        self.learning_rate_decay_a = learning_rate_decay_a
        self.learning_rate_decay_b = learning_rate_decay_b
        self.learning_rate_schedule = learning_rate_schedule

    def to_v2(self):
        """Build the v2 optimizer object for the single engine."""
        if self.learning_rate_decay_a or self.learning_rate_decay_b or \
                self.learning_rate_schedule not in ("poly", "constant"):
            # v1 'poly'/'discexp'/... schedules with nonzero decay have
            # in-graph equivalents, but not through this dialect's
            # constant-lr optimizer objects — refuse rather than train
            # at a silently-constant rate
            raise NotImplementedError(
                "v1 learning_rate_schedule decay is served by the "
                "in-graph schedulers (layers/learning_rate_scheduler.py: "
                "exponential_decay/inverse_time_decay/polynomial_decay); "
                "build the model through the fluid dialect to use them")
        method = self.learning_method or MomentumOptimizer(momentum=0.0)
        common = {"learning_rate": self.learning_rate}
        if isinstance(self.regularization, (L2Regularization,
                                            L1Regularization)):
            # v2 optimizers accept the same regularization objects
            common["regularization"] = v2_opt.L2Regularization(
                self.regularization.rate) \
                if isinstance(self.regularization, L2Regularization) \
                else v2_opt.L1Regularization(self.regularization.rate)
        if self.gradient_clipping_threshold:
            common["gradient_clipping_threshold"] = \
                self.gradient_clipping_threshold
        if self.model_average is not None:
            common["model_average"] = v2_opt.ModelAverage(
                self.model_average.average_window,
                self.model_average.max_average_window)
        return method.to_v2(**common)


_settings = None


def settings(batch_size, learning_rate=1e-3, learning_rate_decay_a=0.0,
             learning_rate_decay_b=0.0, learning_rate_schedule="poly",
             learning_rate_args="", learning_method=None,
             regularization=None, is_async=False, model_average=None,
             gradient_clipping_threshold=None, **deprecated):
    """reference optimizers.py:358.  ``is_async`` selected Async-SGD
    pserver training — out of scope by the SURVEY §2.4 async ruling."""
    if is_async:
        raise NotImplementedError(
            "async pserver SGD has no TPU analog (SURVEY.md §2.4); train "
            "synchronously or use the mesh runtime")
    if learning_method is not None and not isinstance(learning_method,
                                                     BaseSGDOptimizer):
        raise TypeError("learning_method must be a *Optimizer object")
    global _settings
    _settings = TrainingSettings(
        batch_size=batch_size, learning_rate=learning_rate,
        learning_method=learning_method, regularization=regularization,
        gradient_clipping_threshold=gradient_clipping_threshold,
        model_average=model_average,
        learning_rate_decay_a=learning_rate_decay_a,
        learning_rate_decay_b=learning_rate_decay_b,
        learning_rate_schedule=learning_rate_schedule)
    return _settings


def current_settings():
    return _settings


def reset_settings():
    global _settings
    _settings = None
