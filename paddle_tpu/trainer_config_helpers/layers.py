"""v1 layer DSL (reference python/paddle/trainer_config_helpers/layers.py:1).

The v1 configs call ``*_layer`` functions (plus ``mixed_layer`` with
projections) that in the reference mutate a global ``ModelConfig`` proto
consumed by the legacy GradientMachine engine
(``legacy/gserver/layers/``).  Here every call appends fluid-parity ops
to the same process-global Program the v2 dialect builds
(``v2/config.py``) — the v1 *API surface* runs on the single TPU
execution engine.  Curated to the layer set the v1 book/demo configs
use; the v1 recurrence machinery (``memory``/``recurrent_group``/
``beam_search``, reference layers.py recurrent_group) is a documented
design boundary — its capability lives in the fluid-parity
``DynamicRNN``/``layers.beam_search`` stack (layers/control_flow.py).

``LayerOutput`` is the v2 ``Layer`` handle; the two dialects compose
(a v1-built layer can feed a v2 call and vice versa).
"""

from .. import layers as fl
from ..layer_helper import LayerHelper
from ..v2 import config as cfg
from ..v2 import data_type as dt
from ..v2 import layer as v2_layer
from ..v2.activation import act_name
from .poolings import MaxPooling

__all__ = [
    "LayerOutput", "data_layer", "fc_layer", "embedding_layer",
    "mixed_layer", "full_matrix_projection", "identity_projection",
    "table_projection", "dotmul_projection",
    "img_conv_layer", "img_pool_layer", "batch_norm_layer",
    "dropout_layer", "concat_layer", "addto_layer", "pooling_layer",
    "first_seq", "last_seq", "expand_layer", "scaling_layer",
    "slope_intercept_layer", "power_layer", "trans_layer",
    "dot_prod_layer", "cos_sim", "maxid_layer", "lstmemory", "grumemory",
    "classification_cost", "cross_entropy", "square_error_cost",
    "mse_cost", "regression_cost", "multi_binary_label_cross_entropy",
    "smooth_l1_cost", "sum_cost", "nce_layer", "hsigmoid", "crf_layer",
    "crf_decoding_layer", "ctc_layer", "warp_ctc_layer",
    "memory", "recurrent_group", "beam_search", "get_output_layer",
]

LayerOutput = cfg.Layer


def _apply_extra(layer, layer_attr):
    """Honor ExtraLayerAttribute on a built layer: ``drop_rate`` appends
    a dropout op; ``error_clipping_threshold`` sets the output var's
    backward error clip (consumed by clip.error_clip_callback during
    append_backward) — the two v1 extras that are meaningful on this
    stack (attrs.py)."""
    if layer_attr is None:
        return layer
    if getattr(layer_attr, "error_clipping_threshold", None):
        from ..clip import ErrorClipByValue
        layer.var.error_clip = ErrorClipByValue(
            max=layer_attr.error_clipping_threshold)
    if getattr(layer_attr, "drop_rate", None):
        with cfg.build():
            var = fl.dropout(layer.var, dropout_prob=layer_attr.drop_rate)
        return cfg.Layer(var, v2_dim=layer.v2_dim, parents=[layer])
    return layer


def data_layer(name, size, depth=None, height=None, width=None, type=None,
               layer_attr=None):
    """reference layers.py data_layer.  The v1 pipeline took the value
    kind (dense / integer / sequence) from the PyDataProvider2
    declaration; on this stack pass ``type=`` a ``v2.data_type`` object
    for non-dense inputs (default ``dense_vector(size)``) — the provider
    declaration moved into the config call."""
    return v2_layer.data(name, type or dt.dense_vector(size),
                         height=height, width=width)


def fc_layer(input, size, act=None, name=None, param_attr=None,
             bias_attr=None, layer_attr=None):
    return _apply_extra(
        v2_layer.fc(input, size, act=act, param_attr=param_attr,
                    bias_attr=bias_attr, name=name), layer_attr)


def embedding_layer(input, size, name=None, param_attr=None,
                    layer_attr=None):
    return _apply_extra(
        v2_layer.embedding(input, size, param_attr=param_attr, name=name),
        layer_attr)


# ---- mixed_layer + projections -------------------------------------------
#
# v1's mixed_layer sums projection outputs (reference layers.py
# mixed_layer / MixedLayerType); each projection here is a deferred
# recipe producing a Variable of the mixed layer's width.

class BaseProjection(object):
    def build(self, size):
        """Append ops; return the projected Variable of width ``size``
        (or the input's width for identity-style projections)."""
        raise NotImplementedError


class full_matrix_projection(BaseProjection):
    """input x W (reference layers.py full_matrix_projection)."""

    def __init__(self, input, size=0, param_attr=None):
        self.input, self.size, self.param_attr = input, size, param_attr

    def build(self, size):
        size = self.size or size
        nfd = 2 if v2_layer._any_seq([self.input]) else 1
        return fl.fc([self.input.var], size=size, num_flatten_dims=nfd,
                     bias_attr=False, param_attr=self.param_attr)


class identity_projection(BaseProjection):
    """Pass-through, optionally a [offset, offset+size) column slice
    (reference layers.py identity_projection)."""

    def __init__(self, input, offset=None, size=None):
        self.input, self.offset, self.psize = input, offset, size

    def build(self, size):
        var = self.input.var
        if self.offset is None:
            return var
        width = self.psize or size
        ax = len(var.shape) - 1
        return fl.slice(var, axes=[ax], starts=[self.offset],
                        ends=[self.offset + width])


class table_projection(BaseProjection):
    """Embedding lookup on an integer input (reference layers.py
    table_projection)."""

    def __init__(self, input, size=0, param_attr=None):
        self.input, self.size, self.param_attr = input, size, param_attr

    def build(self, size):
        size = self.size or size
        if self.input.v2_dim is None:
            raise ValueError("table_projection input must carry its "
                             "vocabulary size (an integer data layer)")
        return fl.embedding(self.input.var, size=[self.input.v2_dim, size],
                            param_attr=self.param_attr)


class dotmul_projection(BaseProjection):
    """Elementwise scale by a learned [dim] vector (reference layers.py
    dotmul_projection)."""

    def __init__(self, input, param_attr=None):
        self.input, self.param_attr = input, param_attr

    def build(self, size):
        var = self.input.var
        dim = int(var.shape[-1])
        helper = LayerHelper("dotmul_projection", param_attr=self.param_attr)
        w = helper.create_parameter(attr=helper.param_attr, shape=[dim],
                                    dtype=var.dtype)
        return fl.elementwise_mul(var, w)


class MixedLayerType(object):
    """``with mixed_layer(size) as m: m += projection`` builder
    (reference layers.py MixedLayerType).  Also returned pre-finalized
    when ``mixed_layer(input=[...])`` is called directly."""

    def __init__(self, size, act, bias_attr, name):
        self.size, self.act, self.bias_attr, self._name = \
            size, act, bias_attr, name
        self.projections = []
        self.finalized = None

    def __iadd__(self, proj):
        if self.finalized is not None:
            raise ValueError("mixed_layer already finalized")
        if not isinstance(proj, BaseProjection):
            raise TypeError("mixed_layer accepts projection objects, got %r"
                            % (proj,))
        self.projections.append(proj)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self._finalize()
        return False

    def _finalize(self):
        if not self.projections:
            raise ValueError("mixed_layer needs at least one projection")
        with cfg.build():
            vars_ = [p.build(self.size) for p in self.projections]
            out = fl.sums(vars_) if len(vars_) > 1 else vars_[0]
            if self.bias_attr:
                helper = LayerHelper("mixed_bias", bias_attr=self.bias_attr)
                b = helper.create_parameter(
                    attr=helper.bias_attr, shape=[int(out.shape[-1])],
                    dtype=out.dtype, is_bias=True)
                out = fl.elementwise_add(out, b)
            if act_name(self.act):
                out = getattr(fl, act_name(self.act))(out)
            if self._name:
                # identity op carrying the configured name into the
                # program, so lookups by the v1 layer name resolve
                out = fl.scale(out, scale=1.0, name=self._name)
        parents = [p.input for p in self.projections]
        self.finalized = _apply_extra(
            cfg.Layer(out, v2_dim=self.size or None, parents=parents),
            getattr(self, "_layer_attr", None))

    # LayerOutput duck-typing so a finalized mixed_layer feeds other layers
    @property
    def var(self):
        if self.finalized is None:
            self._finalize()
        return self.finalized.var

    @property
    def v2_dim(self):
        return self.finalized.v2_dim if self.finalized else self.size

    @property
    def name(self):
        return self.var.name


from . import layer_math as _layer_math

_layer_math.install_on(MixedLayerType)


def mixed_layer(size=0, input=None, name=None, act=None, bias_attr=False,
                layer_attr=None):
    m = MixedLayerType(size, act, bias_attr, name)
    if input is not None:
        for proj in input if isinstance(input, (list, tuple)) else [input]:
            m += proj
        m._finalize()
        return _apply_extra(m.finalized, layer_attr)
    m._layer_attr = layer_attr
    return m


# ---- image / common layers (delegations) ---------------------------------

def img_conv_layer(input, filter_size, num_filters, num_channels=None,
                   stride=1, padding=0, act=None, groups=1, param_attr=None,
                   bias_attr=None, name=None, shared_biases=True,
                   layer_attr=None, trans=False):
    if trans:
        raise NotImplementedError("transposed img_conv: use "
                                  "layers.conv2d_transpose directly")
    return _apply_extra(
        v2_layer.img_conv(input, filter_size, num_filters,
                          num_channels=num_channels, stride=stride,
                          padding=padding, act=act, groups=groups,
                          param_attr=param_attr, bias_attr=bias_attr,
                          name=name), layer_attr)


def img_pool_layer(input, pool_size, num_channels=None, pool_type=None,
                   stride=1, padding=0, name=None, ceil_mode=False,
                   pool_size_y=None, stride_y=None, padding_y=None,
                   layer_attr=None, exclude_mode=None):
    """reference layers.py img_pool_layer.  The geometry kwargs the v1
    engine honored (ceil_mode, non-square *_y variants) all reach
    pool2d — dropping any of them would silently change output dims."""
    from ..v2.pooling import img_pool_type

    def _hw(x, y):
        # v1 *_y kwargs default to the x value; pool2d takes [H, W]
        return x if y is None else [y, x]

    with cfg.build():
        img, _c = v2_layer._as_image(input, num_channels)
        var = fl.pool2d(img, pool_size=_hw(pool_size, pool_size_y),
                        pool_type=img_pool_type(pool_type or MaxPooling()),
                        pool_stride=_hw(stride, stride_y),
                        pool_padding=_hw(padding, padding_y),
                        ceil_mode=ceil_mode, name=name)
    return _apply_extra(cfg.Layer(var, parents=[input]), layer_attr)


def batch_norm_layer(input, act=None, name=None, num_channels=None,
                     bias_attr=None, param_attr=None, layer_attr=None,
                     use_global_stats=None, moving_average_fraction=0.9,
                     batch_norm_type=None, mean_var_names=None):
    return _apply_extra(v2_layer.batch_norm(
        input, act=act, name=name, num_channels=num_channels,
        param_attr=param_attr, bias_attr=bias_attr,
        use_global_stats=use_global_stats,
        moving_average_fraction=moving_average_fraction), layer_attr)


def dropout_layer(input, dropout_rate, name=None):
    return v2_layer.dropout(input, dropout_rate, name=name)


def concat_layer(input, act=None, name=None, layer_attr=None,
                 bias_attr=None):
    return _apply_extra(v2_layer.concat(input, act=act, name=name),
                        layer_attr)


def addto_layer(input, act=None, name=None, bias_attr=None,
                layer_attr=None):
    return _apply_extra(
        v2_layer.addto(input, act=act, bias_attr=bias_attr, name=name),
        layer_attr)


def pooling_layer(input, pooling_type=None, name=None, bias_attr=None,
                  agg_level=None, layer_attr=None):
    return _apply_extra(
        v2_layer.pooling(input, pooling_type=pooling_type or MaxPooling(),
                         agg_level=agg_level, name=name), layer_attr)


def first_seq(input, name=None, layer_attr=None, **kwargs):
    return _apply_extra(v2_layer.first_seq(input, name=name, **kwargs),
                        layer_attr)


def last_seq(input, name=None, layer_attr=None, **kwargs):
    return _apply_extra(v2_layer.last_seq(input, name=name, **kwargs),
                        layer_attr)


def cos_sim(a, b, scale=1, name=None, layer_attr=None):
    return _apply_extra(v2_layer.cos_sim(a, b, scale=scale, name=name),
                        layer_attr)


def maxid_layer(input, name=None, layer_attr=None):
    return _apply_extra(v2_layer.max_id(input, name=name), layer_attr)


def lstmemory(input, size=None, reverse=False, act=None, gate_act=None,
              state_act=None, bias_attr=None, param_attr=None, name=None,
              layer_attr=None):
    return _apply_extra(
        v2_layer.lstmemory(input, size=size, reverse=reverse, act=act,
                           gate_act=gate_act, state_act=state_act,
                           bias_attr=bias_attr, param_attr=param_attr,
                           name=name), layer_attr)


def grumemory(input, size=None, reverse=False, act=None, gate_act=None,
              bias_attr=None, param_attr=None, name=None, layer_attr=None):
    return _apply_extra(
        v2_layer.grumemory(input, size=size, reverse=reverse, act=act,
                           gate_act=gate_act, bias_attr=bias_attr,
                           param_attr=param_attr, name=name), layer_attr)


def expand_layer(input, expand_as, name=None, bias_attr=False,
                 expand_level=None, layer_attr=None):
    """Broadcast per-sequence values across the timesteps of ``expand_as``
    (reference layers.py expand_layer -> sequence_expand)."""
    with cfg.build():
        var = fl.sequence_expand(input.var, expand_as.var)
    return _apply_extra(cfg.Layer(var, v2_dim=input.v2_dim,
                                  parents=[input, expand_as]), layer_attr)


def scaling_layer(input, weight, name=None, layer_attr=None):
    """Per-sample scalar multiply: weight is [B, 1] (reference layers.py
    scaling_layer)."""
    with cfg.build():
        var = fl.elementwise_mul(input.var, weight.var)
    return _apply_extra(cfg.Layer(var, v2_dim=input.v2_dim,
                                  parents=[input, weight]), layer_attr)


def slope_intercept_layer(input, name=None, slope=1.0, intercept=0.0,
                          layer_attr=None):
    """y = slope * x + intercept (reference layers.py
    slope_intercept_layer; the layer_math workhorse)."""
    with cfg.build():
        var = fl.scale(input.var, scale=float(slope), bias=float(intercept))
    return _apply_extra(cfg.Layer(var, v2_dim=input.v2_dim,
                                  parents=[input]), layer_attr)


def power_layer(input, weight, name=None, layer_attr=None):
    """y = x ** w with w a per-sample [B, 1] scalar (reference layers.py
    power_layer)."""
    with cfg.build():
        helper = LayerHelper("power")
        out = helper.create_variable_for_type_inference(input.var.dtype)
        helper.append_op(type="elementwise_pow",
                         inputs={"X": [input.var], "Y": [weight.var]},
                         outputs={"Out": [out]})
    return _apply_extra(cfg.Layer(out, v2_dim=input.v2_dim,
                                  parents=[input, weight]), layer_attr)


def trans_layer(input, name=None, layer_attr=None):
    """Matrix transpose of a [B, N] -> [N, B] layer (reference layers.py
    trans_layer)."""
    with cfg.build():
        var = fl.transpose(input.var, perm=[1, 0])
    return _apply_extra(cfg.Layer(var, parents=[input]), layer_attr)


def dot_prod_layer(input1, input2, name=None, layer_attr=None):
    """Row-wise dot product -> [B, 1] (reference layers.py
    dot_prod_layer)."""
    with cfg.build():
        var = fl.reduce_sum(fl.elementwise_mul(input1.var, input2.var),
                            dim=-1, keep_dim=True)
    return _apply_extra(cfg.Layer(var, v2_dim=1,
                                  parents=[input1, input2]), layer_attr)


# ---- cost layers ----------------------------------------------------------

classification_cost = v2_layer.classification_cost
cross_entropy = v2_layer.cross_entropy_cost
square_error_cost = v2_layer.square_error_cost
mse_cost = v2_layer.square_error_cost
regression_cost = v2_layer.square_error_cost
multi_binary_label_cross_entropy = \
    v2_layer.multi_binary_label_cross_entropy_cost


def smooth_l1_cost(input, label, name=None, coeff=1.0, layer_attr=None):
    with cfg.build():
        cost = fl.mean(fl.smooth_l1(input.var, label.var))
        if coeff != 1.0:
            cost = cost * coeff
    return cfg.Layer(cost, parents=[input, label])


def sum_cost(input, name=None, layer_attr=None):
    with cfg.build():
        cost = fl.reduce_sum(input.var)
    return cfg.Layer(cost, parents=[input])


def nce_layer(input, label, num_classes=None, param_attr=None, weight=None,
              num_neg_samples=10, neg_distribution=None, bias_attr=None,
              name=None, layer_attr=None):
    return v2_layer.nce(input, label, num_classes, param_attr=param_attr,
                        weight=weight, num_neg_samples=num_neg_samples,
                        neg_distribution=neg_distribution,
                        bias_attr=bias_attr, name=name)


hsigmoid = v2_layer.hsigmoid
crf_layer = v2_layer.crf
crf_decoding_layer = v2_layer.crf_decoding
ctc_layer = v2_layer.ctc
warp_ctc_layer = v2_layer.ctc


# ---- v1 recurrence machinery: documented design boundary ------------------

def memory(*args, **kwargs):
    raise NotImplementedError(
        "v1 memory/recurrent_group (reference layers.py recurrent_group) "
        "is a design boundary: step-level recurrence on this stack is the "
        "fluid-parity DynamicRNN/StaticRNN (layers/control_flow.py), which "
        "compiles to lax.scan instead of per-step proto sub-models")


recurrent_group = memory
get_output_layer = memory


def beam_search(*args, **kwargs):
    raise NotImplementedError(
        "v1 beam_search generation is served by the fluid-parity "
        "layers.beam_search / beam_search_decode ops (ops/ beam search "
        "family); see tests/test_rnn_encoder_decoder.py")
