"""v1 layer DSL (reference python/paddle/trainer_config_helpers/layers.py:1).

The v1 configs call ``*_layer`` functions (plus ``mixed_layer`` with
projections) that in the reference mutate a global ``ModelConfig`` proto
consumed by the legacy GradientMachine engine
(``legacy/gserver/layers/``).  Here every call appends fluid-parity ops
to the same process-global Program the v2 dialect builds
(``v2/config.py``) — the v1 *API surface* runs on the single TPU
execution engine.  The full reference ``__all__`` is served (the
parity tail below covers the long tail of v1-only layers); the v1
recurrence machinery (``memory``/``recurrent_group``/``beam_search``,
reference layers.py recurrent_group) is a documented design boundary —
its capability lives in the fluid-parity ``DynamicRNN``/
``layers.beam_search`` stack (layers/control_flow.py) — and nested-LoD
names (``sub_nested_seq_layer``) raise with the SURVEY §5 one-level
ruling.

``LayerOutput`` is the v2 ``Layer`` handle; the two dialects compose
(a v1-built layer can feed a v2 call and vice versa).
"""

from .. import layers as fl
from ..layer_helper import LayerHelper
from ..v2 import config as cfg
from ..v2 import data_type as dt
from ..v2 import layer as v2_layer
from ..v2.activation import act_name
from .poolings import MaxPooling

__all__ = [
    "LayerOutput", "data_layer", "fc_layer", "embedding_layer",
    "mixed_layer", "full_matrix_projection", "identity_projection",
    "table_projection", "dotmul_projection",
    "img_conv_layer", "img_pool_layer", "batch_norm_layer",
    "dropout_layer", "concat_layer", "addto_layer", "pooling_layer",
    "first_seq", "last_seq", "expand_layer", "scaling_layer",
    "slope_intercept_layer", "power_layer", "trans_layer",
    "dot_prod_layer", "cos_sim", "maxid_layer", "lstmemory", "grumemory",
    "classification_cost", "cross_entropy", "square_error_cost",
    "mse_cost", "regression_cost", "multi_binary_label_cross_entropy",
    "smooth_l1_cost", "sum_cost", "nce_layer", "hsigmoid", "crf_layer",
    "crf_decoding_layer", "ctc_layer", "warp_ctc_layer",
    "memory", "recurrent_group", "beam_search", "get_output_layer",
    "LayerType",
    "AggregateLevel",
    "ExpandLevel",
    "layer_support",
    "StaticInput",
    "BaseGeneratedInput",
    "GeneratedInput",
    "SubsequenceInput",
    "BeamInput",
    "trans_full_matrix_projection",
    "scaling_projection",
    "slice_projection",
    "context_projection",
    "dotmul_operator",
    "conv_operator",
    "conv_projection",
    "clip_layer",
    "maxout_layer",
    "prelu_layer",
    "pad_layer",
    "crop_layer",
    "rotate_layer",
    "switch_order_layer",
    "resize_layer",
    "repeat_layer",
    "upsample_layer",
    "bilinear_interp_layer",
    "interpolation_layer",
    "linear_comb_layer",
    "convex_comb_layer",
    "out_prod_layer",
    "tensor_layer",
    "scale_shift_layer",
    "scale_sub_region_layer",
    "sum_to_one_norm_layer",
    "row_l2_norm_layer",
    "l2_distance_layer",
    "multiplex_layer",
    "eos_layer",
    "sampling_id_layer",
    "print_layer",
    "printer_layer",
    "img_cmrnorm_layer",
    "cross_channel_norm_layer",
    "spp_layer",
    "img_conv3d_layer",
    "img_pool3d_layer",
    "block_expand_layer",
    "priorbox_layer",
    "detection_output_layer",
    "multibox_loss_layer",
    "roi_pool_layer",
    "seq_concat_layer",
    "seq_reshape_layer",
    "seq_slice_layer",
    "sub_seq_layer",
    "sub_nested_seq_layer",
    "kmax_seq_score_layer",
    "recurrent_layer",
    "lstm_step_layer",
    "gru_step_layer",
    "gru_step_naive_layer",
    "gated_unit_layer",
    "selective_fc_layer",
    "factorization_machine",
    "rank_cost",
    "huber_regression_cost",
    "huber_classification_cost",
    "cross_entropy_with_selfnorm",
    "lambda_cost",
    "cross_entropy_over_beam",
    "conv_shift_layer",
    "row_conv_layer",
]

LayerOutput = cfg.Layer


def _apply_extra(layer, layer_attr):
    """Honor ExtraLayerAttribute on a built layer: ``drop_rate`` appends
    a dropout op; ``error_clipping_threshold`` sets the output var's
    backward error clip (consumed by clip.error_clip_callback during
    append_backward) — the two v1 extras that are meaningful on this
    stack (attrs.py)."""
    if layer_attr is None:
        return layer
    if getattr(layer_attr, "error_clipping_threshold", None):
        from ..clip import ErrorClipByValue
        layer.var.error_clip = ErrorClipByValue(
            max=layer_attr.error_clipping_threshold)
    if getattr(layer_attr, "drop_rate", None):
        with cfg.build():
            var = fl.dropout(layer.var, dropout_prob=layer_attr.drop_rate)
        return cfg.Layer(var, v2_dim=layer.v2_dim, parents=[layer])
    return layer


def data_layer(name, size, depth=None, height=None, width=None, type=None,
               layer_attr=None):
    """reference layers.py data_layer.  The v1 pipeline took the value
    kind (dense / integer / sequence) from the PyDataProvider2
    declaration; on this stack pass ``type=`` a ``v2.data_type`` object
    for non-dense inputs (default ``dense_vector(size)``) — the provider
    declaration moved into the config call."""
    return v2_layer.data(name, type or dt.dense_vector(size),
                         height=height, width=width)


def fc_layer(input, size, act=None, name=None, param_attr=None,
             bias_attr=None, layer_attr=None):
    return _apply_extra(
        v2_layer.fc(input, size, act=act, param_attr=param_attr,
                    bias_attr=bias_attr, name=name), layer_attr)


def embedding_layer(input, size, name=None, param_attr=None,
                    layer_attr=None):
    return _apply_extra(
        v2_layer.embedding(input, size, param_attr=param_attr, name=name),
        layer_attr)


# ---- mixed_layer + projections -------------------------------------------
#
# v1's mixed_layer sums projection outputs (reference layers.py
# mixed_layer / MixedLayerType); each projection here is a deferred
# recipe producing a Variable of the mixed layer's width.

class BaseProjection(object):
    def build(self, size):
        """Append ops; return the projected Variable of width ``size``
        (or the input's width for identity-style projections)."""
        raise NotImplementedError


class full_matrix_projection(BaseProjection):
    """input x W (reference layers.py full_matrix_projection)."""

    def __init__(self, input, size=0, param_attr=None):
        self.input, self.size, self.param_attr = input, size, param_attr

    def build(self, size):
        size = self.size or size
        nfd = 2 if v2_layer._any_seq([self.input]) else 1
        return fl.fc([self.input.var], size=size, num_flatten_dims=nfd,
                     bias_attr=False, param_attr=self.param_attr)


class identity_projection(BaseProjection):
    """Pass-through, optionally a [offset, offset+size) column slice
    (reference layers.py identity_projection)."""

    def __init__(self, input, offset=None, size=None):
        self.input, self.offset, self.psize = input, offset, size

    def build(self, size):
        var = self.input.var
        if self.offset is None:
            return var
        width = self.psize or size
        ax = len(var.shape) - 1
        return fl.slice(var, axes=[ax], starts=[self.offset],
                        ends=[self.offset + width])


class table_projection(BaseProjection):
    """Embedding lookup on an integer input (reference layers.py
    table_projection)."""

    def __init__(self, input, size=0, param_attr=None):
        self.input, self.size, self.param_attr = input, size, param_attr

    def build(self, size):
        size = self.size or size
        if self.input.v2_dim is None:
            raise ValueError("table_projection input must carry its "
                             "vocabulary size (an integer data layer)")
        return fl.embedding(self.input.var, size=[self.input.v2_dim, size],
                            param_attr=self.param_attr)


class dotmul_projection(BaseProjection):
    """Elementwise scale by a learned [dim] vector (reference layers.py
    dotmul_projection)."""

    def __init__(self, input, param_attr=None):
        self.input, self.param_attr = input, param_attr

    def build(self, size):
        var = self.input.var
        dim = int(var.shape[-1])
        helper = LayerHelper("dotmul_projection", param_attr=self.param_attr)
        w = helper.create_parameter(attr=helper.param_attr, shape=[dim],
                                    dtype=var.dtype)
        return fl.elementwise_mul(var, w)


class MixedLayerType(object):
    """``with mixed_layer(size) as m: m += projection`` builder
    (reference layers.py MixedLayerType).  Also returned pre-finalized
    when ``mixed_layer(input=[...])`` is called directly."""

    def __init__(self, size, act, bias_attr, name):
        self.size, self.act, self.bias_attr, self._name = \
            size, act, bias_attr, name
        self.projections = []
        self.finalized = None

    def __iadd__(self, proj):
        if self.finalized is not None:
            raise ValueError("mixed_layer already finalized")
        if not isinstance(proj, BaseProjection):
            raise TypeError("mixed_layer accepts projection objects, got %r"
                            % (proj,))
        self.projections.append(proj)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self._finalize()
        return False

    def _finalize(self):
        if not self.projections:
            raise ValueError("mixed_layer needs at least one projection")
        with cfg.build():
            vars_ = [p.build(self.size) for p in self.projections]
            out = fl.sums(vars_) if len(vars_) > 1 else vars_[0]
            if self.bias_attr:
                helper = LayerHelper("mixed_bias", bias_attr=self.bias_attr)
                b = helper.create_parameter(
                    attr=helper.bias_attr, shape=[int(out.shape[-1])],
                    dtype=out.dtype, is_bias=True)
                out = fl.elementwise_add(out, b)
            if act_name(self.act):
                out = getattr(fl, act_name(self.act))(out)
            if self._name:
                # identity op carrying the configured name into the
                # program, so lookups by the v1 layer name resolve
                out = fl.scale(out, scale=1.0, name=self._name)
        parents = [p.input for p in self.projections
                   if getattr(p, 'input', None) is not None]
        self.finalized = _apply_extra(
            cfg.Layer(out, v2_dim=self.size or None, parents=parents),
            getattr(self, "_layer_attr", None))

    # LayerOutput duck-typing so a finalized mixed_layer feeds other layers
    @property
    def var(self):
        if self.finalized is None:
            self._finalize()
        return self.finalized.var

    @property
    def v2_dim(self):
        return self.finalized.v2_dim if self.finalized else self.size

    @property
    def name(self):
        return self.var.name


from . import layer_math as _layer_math

_layer_math.install_on(MixedLayerType)


def mixed_layer(size=0, input=None, name=None, act=None, bias_attr=False,
                layer_attr=None):
    m = MixedLayerType(size, act, bias_attr, name)
    if input is not None:
        for proj in input if isinstance(input, (list, tuple)) else [input]:
            m += proj
        m._finalize()
        return _apply_extra(m.finalized, layer_attr)
    m._layer_attr = layer_attr
    return m


# ---- image / common layers (delegations) ---------------------------------

def img_conv_layer(input, filter_size, num_filters, num_channels=None,
                   stride=1, padding=0, act=None, groups=1, param_attr=None,
                   bias_attr=None, name=None, shared_biases=True,
                   layer_attr=None, trans=False):
    if trans:
        raise NotImplementedError("transposed img_conv: use "
                                  "layers.conv2d_transpose directly")
    return _apply_extra(
        v2_layer.img_conv(input, filter_size, num_filters,
                          num_channels=num_channels, stride=stride,
                          padding=padding, act=act, groups=groups,
                          param_attr=param_attr, bias_attr=bias_attr,
                          name=name), layer_attr)


def img_pool_layer(input, pool_size, num_channels=None, pool_type=None,
                   stride=1, padding=0, name=None, ceil_mode=False,
                   pool_size_y=None, stride_y=None, padding_y=None,
                   layer_attr=None, exclude_mode=None):
    """reference layers.py img_pool_layer.  The geometry kwargs the v1
    engine honored (ceil_mode, non-square *_y variants) all reach
    pool2d — dropping any of them would silently change output dims."""
    from ..v2.pooling import img_pool_type

    def _hw(x, y):
        # v1 *_y kwargs default to the x value; pool2d takes [H, W]
        return x if y is None else [y, x]

    with cfg.build():
        img, _c = v2_layer._as_image(input, num_channels)
        var = fl.pool2d(img, pool_size=_hw(pool_size, pool_size_y),
                        pool_type=img_pool_type(pool_type or MaxPooling()),
                        pool_stride=_hw(stride, stride_y),
                        pool_padding=_hw(padding, padding_y),
                        ceil_mode=ceil_mode, name=name)
    return _apply_extra(cfg.Layer(var, parents=[input]), layer_attr)


def batch_norm_layer(input, act=None, name=None, num_channels=None,
                     bias_attr=None, param_attr=None, layer_attr=None,
                     use_global_stats=None, moving_average_fraction=0.9,
                     batch_norm_type=None, mean_var_names=None):
    return _apply_extra(v2_layer.batch_norm(
        input, act=act, name=name, num_channels=num_channels,
        param_attr=param_attr, bias_attr=bias_attr,
        use_global_stats=use_global_stats,
        moving_average_fraction=moving_average_fraction), layer_attr)


def dropout_layer(input, dropout_rate, name=None):
    return v2_layer.dropout(input, dropout_rate, name=name)


def concat_layer(input, act=None, name=None, layer_attr=None,
                 bias_attr=None):
    return _apply_extra(v2_layer.concat(input, act=act, name=name),
                        layer_attr)


def addto_layer(input, act=None, name=None, bias_attr=None,
                layer_attr=None):
    return _apply_extra(
        v2_layer.addto(input, act=act, bias_attr=bias_attr, name=name),
        layer_attr)


def pooling_layer(input, pooling_type=None, name=None, bias_attr=None,
                  agg_level=None, layer_attr=None):
    return _apply_extra(
        v2_layer.pooling(input, pooling_type=pooling_type or MaxPooling(),
                         agg_level=agg_level, name=name), layer_attr)


def first_seq(input, name=None, layer_attr=None, **kwargs):
    return _apply_extra(v2_layer.first_seq(input, name=name, **kwargs),
                        layer_attr)


def last_seq(input, name=None, layer_attr=None, **kwargs):
    return _apply_extra(v2_layer.last_seq(input, name=name, **kwargs),
                        layer_attr)


def cos_sim(a, b, scale=1, name=None, layer_attr=None):
    return _apply_extra(v2_layer.cos_sim(a, b, scale=scale, name=name),
                        layer_attr)


def maxid_layer(input, name=None, layer_attr=None):
    return _apply_extra(v2_layer.max_id(input, name=name), layer_attr)


def lstmemory(input, size=None, reverse=False, act=None, gate_act=None,
              state_act=None, bias_attr=None, param_attr=None, name=None,
              layer_attr=None):
    return _apply_extra(
        v2_layer.lstmemory(input, size=size, reverse=reverse, act=act,
                           gate_act=gate_act, state_act=state_act,
                           bias_attr=bias_attr, param_attr=param_attr,
                           name=name), layer_attr)


def grumemory(input, size=None, reverse=False, act=None, gate_act=None,
              bias_attr=None, param_attr=None, name=None, layer_attr=None):
    return _apply_extra(
        v2_layer.grumemory(input, size=size, reverse=reverse, act=act,
                           gate_act=gate_act, bias_attr=bias_attr,
                           param_attr=param_attr, name=name), layer_attr)


def expand_layer(input, expand_as, name=None, bias_attr=False,
                 expand_level=None, layer_attr=None):
    """Broadcast per-sequence values across the timesteps of ``expand_as``
    (reference layers.py expand_layer -> sequence_expand)."""
    with cfg.build():
        var = fl.sequence_expand(input.var, expand_as.var)
    return _apply_extra(cfg.Layer(var, v2_dim=input.v2_dim,
                                  parents=[input, expand_as]), layer_attr)


def scaling_layer(input, weight, name=None, layer_attr=None):
    """Per-sample scalar multiply: weight is [B, 1] (reference layers.py
    scaling_layer)."""
    with cfg.build():
        var = fl.elementwise_mul(input.var, weight.var)
    return _apply_extra(cfg.Layer(var, v2_dim=input.v2_dim,
                                  parents=[input, weight]), layer_attr)


def slope_intercept_layer(input, name=None, slope=1.0, intercept=0.0,
                          layer_attr=None):
    """y = slope * x + intercept (reference layers.py
    slope_intercept_layer; the layer_math workhorse)."""
    with cfg.build():
        var = fl.scale(input.var, scale=float(slope), bias=float(intercept))
    return _apply_extra(cfg.Layer(var, v2_dim=input.v2_dim,
                                  parents=[input]), layer_attr)


def power_layer(input, weight, name=None, layer_attr=None):
    """y = x ** w with w a per-sample [B, 1] scalar (reference layers.py
    power_layer)."""
    with cfg.build():
        helper = LayerHelper("power")
        out = helper.create_variable_for_type_inference(input.var.dtype)
        helper.append_op(type="elementwise_pow",
                         inputs={"X": [input.var], "Y": [weight.var]},
                         outputs={"Out": [out]})
    return _apply_extra(cfg.Layer(out, v2_dim=input.v2_dim,
                                  parents=[input, weight]), layer_attr)


def trans_layer(input, name=None, layer_attr=None):
    """Matrix transpose of a [B, N] -> [N, B] layer (reference layers.py
    trans_layer)."""
    with cfg.build():
        var = fl.transpose(input.var, perm=[1, 0])
    return _apply_extra(cfg.Layer(var, parents=[input]), layer_attr)


def dot_prod_layer(input1, input2, name=None, layer_attr=None):
    """Row-wise dot product -> [B, 1] (reference layers.py
    dot_prod_layer)."""
    with cfg.build():
        var = fl.reduce_sum(fl.elementwise_mul(input1.var, input2.var),
                            dim=-1, keep_dim=True)
    return _apply_extra(cfg.Layer(var, v2_dim=1,
                                  parents=[input1, input2]), layer_attr)


# ---- cost layers ----------------------------------------------------------

classification_cost = v2_layer.classification_cost
cross_entropy = v2_layer.cross_entropy_cost
square_error_cost = v2_layer.square_error_cost
mse_cost = v2_layer.square_error_cost
regression_cost = v2_layer.square_error_cost
multi_binary_label_cross_entropy = \
    v2_layer.multi_binary_label_cross_entropy_cost


def smooth_l1_cost(input, label, name=None, coeff=1.0, layer_attr=None):
    with cfg.build():
        cost = fl.mean(fl.smooth_l1(input.var, label.var))
        if coeff != 1.0:
            cost = cost * coeff
    return cfg.Layer(cost, parents=[input, label])


def sum_cost(input, name=None, layer_attr=None):
    with cfg.build():
        cost = fl.reduce_sum(input.var)
    return cfg.Layer(cost, parents=[input])


def nce_layer(input, label, num_classes=None, param_attr=None, weight=None,
              num_neg_samples=10, neg_distribution=None, bias_attr=None,
              name=None, layer_attr=None):
    return v2_layer.nce(input, label, num_classes, param_attr=param_attr,
                        weight=weight, num_neg_samples=num_neg_samples,
                        neg_distribution=neg_distribution,
                        bias_attr=bias_attr, name=name)


hsigmoid = v2_layer.hsigmoid
crf_layer = v2_layer.crf
crf_decoding_layer = v2_layer.crf_decoding
ctc_layer = v2_layer.ctc
warp_ctc_layer = v2_layer.ctc


# ---- v1 recurrence machinery: documented design boundary ------------------

def memory(*args, **kwargs):
    raise NotImplementedError(
        "v1 memory/recurrent_group (reference layers.py recurrent_group) "
        "is a design boundary: step-level recurrence on this stack is the "
        "fluid-parity DynamicRNN/StaticRNN (layers/control_flow.py), which "
        "compiles to lax.scan instead of per-step proto sub-models")


recurrent_group = memory
get_output_layer = memory


def beam_search(*args, **kwargs):
    raise NotImplementedError(
        "v1 beam_search generation is served by the fluid-parity "
        "layers.beam_search / beam_search_decode ops (ops/ beam search "
        "family); see tests/test_rnn_encoder_decoder.py")


# ===========================================================================
# parity tail: the remaining reference layers.py names.  Same conventions
# as above — build fluid-parity ops under cfg.build(), wrap in cfg.Layer.
# ===========================================================================

# ---- markers / enums (reference layers.py LayerType, AggregateLevel,
# ExpandLevel; config introspection + recurrent_group input markers) -------

class LayerType(object):
    """Layer-type name constants (reference layers.py LayerType).  On
    this stack layer identity is the op graph, so these are tags for
    config-introspection parity."""
    DATA = "data"
    FC_LAYER = "fc"
    MIXED_LAYER = "mixed"
    COST = "cost"

    @staticmethod
    def is_layer_type(type_name):
        return isinstance(type_name, str)


class AggregateLevel(object):
    """reference layers.py AggregateLevel (sequence pooling levels).
    One LoD level exists here, so both levels name the same axis."""
    TO_NO_SEQUENCE = "non-seq"
    TO_SEQUENCE = "seq"
    EACH_SEQUENCE = "seq"
    EACH_TIMESTEP = "non-seq"


class ExpandLevel(object):
    """reference layers.py ExpandLevel (expand_layer targets)."""
    FROM_NO_SEQUENCE = AggregateLevel.TO_NO_SEQUENCE
    FROM_SEQUENCE = AggregateLevel.TO_SEQUENCE
    FROM_TIMESTEP = AggregateLevel.TO_NO_SEQUENCE


def layer_support(*attrs):
    """reference layers.py layer_support decorator: declares which extra
    attributes a layer honors.  Attribute handling here is explicit
    (_apply_extra), so this is an identity decorator kept for parity."""
    def decorator(fn):
        return fn
    return decorator


class StaticInput(object):
    """Unstepped input marker for the v1 recurrent_group (reference
    layers.py StaticInput).  Constructible for config parity; consumed
    only by recurrent_group, which is a documented design boundary."""

    def __init__(self, input, is_seq=False, size=None):
        self.input, self.is_seq, self.size = input, is_seq, size


class BaseGeneratedInput(object):
    def __init__(self):
        self.bos_id = None
        self.eos_id = None


class GeneratedInput(BaseGeneratedInput):
    """Generation-mode input marker (reference layers.py
    GeneratedInput); generation on this stack is layers.beam_search."""

    def __init__(self, size, embedding_name, embedding_size, name=None):
        super().__init__()
        self.size = size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size
        self.name = name


class SubsequenceInput(object):
    """Nested-sequence step marker (reference layers.py
    SubsequenceInput): multi-level LoD is a documented boundary of the
    padded+@LEN design (SURVEY §5)."""

    def __init__(self, input):
        self.input = input


class BeamInput(object):
    """cross_entropy_over_beam input triple (reference layers.py
    BeamInput)."""

    def __init__(self, candidate_scores, selected_candidates, gold):
        self.candidate_scores = candidate_scores
        self.selected_candidates = selected_candidates
        self.gold = gold


# ---- additional projections / operators for mixed_layer -------------------

class trans_full_matrix_projection(BaseProjection):
    """input x W^T (reference layers.py trans_full_matrix_projection:
    the weight is stored transposed, useful for weight tying)."""

    def __init__(self, input, size=0, param_attr=None):
        self.input, self.size, self.param_attr = input, size, param_attr

    def build(self, size):
        size = self.size or size
        var = self.input.var
        helper = LayerHelper("trans_fmp", param_attr=self.param_attr)
        w = helper.create_parameter(attr=helper.param_attr,
                                    shape=[size, int(var.shape[-1])],
                                    dtype=var.dtype)
        return fl.matmul(var, w, transpose_y=True)


class scaling_projection(BaseProjection):
    """A single learned scalar times the input (reference layers.py
    scaling_projection)."""

    def __init__(self, input, param_attr=None):
        self.input, self.param_attr = input, param_attr

    def build(self, size):
        var = self.input.var
        helper = LayerHelper("scaling_projection",
                             param_attr=self.param_attr)
        w = helper.create_parameter(attr=helper.param_attr, shape=[1],
                                    dtype=var.dtype)
        return fl.elementwise_mul(var, w)


class slice_projection(BaseProjection):
    """Concat of column slices [(start, end), ...] (reference layers.py
    slice_projection)."""

    def __init__(self, input, slices):
        for s in slices:
            if len(s) != 2 or s[0] >= s[1]:
                raise ValueError("invalid slice %r" % (s,))
        self.input, self.slices = input, slices

    def build(self, size):
        var = self.input.var
        ax = len(var.shape) - 1
        parts = [fl.slice(var, axes=[ax], starts=[s], ends=[e])
                 for s, e in self.slices]
        return parts[0] if len(parts) == 1 else fl.concat(parts, axis=ax)


class context_projection(BaseProjection):
    """Concat a sliding window of neighboring timesteps (reference
    layers.py context_projection): for context_len L starting at
    context_start, each timestep becomes the concat of L neighbors
    (zero-padded at the edges).  Padded [B, T, D] shifts via pad+slice."""

    def __init__(self, input, context_len, context_start=None,
                 padding_attr=False):
        self.input = input
        self.context_len = context_len
        self.context_start = context_start if context_start is not None \
            else -(context_len // 2)
        if padding_attr not in (False, None):
            raise NotImplementedError(
                "trainable context padding (reference context_projection "
                "padding_attr) is out of scope; zeros pad the edges")

    def build(self, size):
        var = self.input.var          # [B, T, D]
        outs = []
        for k in range(self.context_len):
            off = self.context_start + k
            if off == 0:
                outs.append(var)
                continue
            if off > 0:     # look ahead: drop first rows, pad at end
                padded = fl.pad(var, paddings=[0, 0, 0, off, 0, 0])
                shifted = fl.slice(padded, axes=[1], starts=[off],
                                   ends=[int(1e9)])
            else:           # look back: pad at front, drop the tail
                padded = fl.pad(var, paddings=[0, 0, -off, 0, 0, 0])
                # negative end: stop |off| before the padded end -> T
                shifted = fl.slice(padded, axes=[1], starts=[0],
                                   ends=[off])
            outs.append(shifted)
        return fl.concat(outs, axis=2)


class dotmul_operator(BaseProjection):
    """Elementwise a*b*scale joining two mixed inputs (reference
    layers.py dotmul_operator)."""

    def __init__(self, a=None, b=None, scale=1.0):
        self.a, self.b, self.scale = a, b, scale
        self.input = a

    def build(self, size):
        out = fl.elementwise_mul(self.a.var, self.b.var)
        if self.scale != 1.0:
            out = fl.scale(out, scale=float(self.scale))
        return out


class conv_operator(BaseProjection):
    """Conv joining an image input and a filter-shaped input is the
    reference's exotic use; the common conv-in-mixed form (this one)
    convolves the image with a LEARNED filter (reference layers.py
    conv_operator/conv_projection share ConvOperator)."""

    def __init__(self, img, filter, filter_size, num_filters,
                 num_channels=None, stride=1, padding=0,
                 filter_size_y=None, stride_y=None, padding_y=None):
        if filter is not None:
            raise NotImplementedError(
                "conv_operator with a dynamic filter input maps to no "
                "XLA-friendly op; use conv_projection (learned filter)")
        self.img = img
        self.filter_size, self.num_filters = filter_size, num_filters
        self.num_channels, self.stride, self.padding = \
            num_channels, stride, padding

    def build(self, size):
        img, _c = v2_layer._as_image(self.img, self.num_channels)
        out = fl.conv2d(img, num_filters=self.num_filters,
                        filter_size=self.filter_size, stride=self.stride,
                        padding=self.padding, bias_attr=False)
        return fl.reshape(out, shape=[0, -1])


class conv_projection(conv_operator):
    """Learned-filter conv projection (reference layers.py
    conv_projection)."""

    def __init__(self, input, filter_size, num_filters, num_channels=None,
                 stride=1, padding=0, param_attr=None, **kwargs):
        super().__init__(input, None, filter_size, num_filters,
                         num_channels, stride, padding)


# ---- elementwise / geometric layers ---------------------------------------

def _wrap1(layer, var, dim=None):
    return cfg.Layer(var, v2_dim=dim, parents=[layer])


def clip_layer(input, min, max, name=None, layer_attr=None):
    with cfg.build():
        var = fl.clip(input.var, min=float(min), max=float(max))
    return _apply_extra(_wrap1(input, var, input.v2_dim), layer_attr)


def maxout_layer(input, groups, num_channels=None, name=None,
                 layer_attr=None):
    with cfg.build():
        img, _c = v2_layer._as_image(input, num_channels)
        var = fl.maxout(img, groups=groups)
    return _apply_extra(_wrap1(input, var), layer_attr)


def prelu_layer(input, name=None, partial_sum=1, param_attr=None,
                layer_attr=None):
    with cfg.build():
        mode = "all" if partial_sum != 1 else "element"
        var = fl.prelu(input.var, mode=mode, param_attr=param_attr)
    return _apply_extra(_wrap1(input, var, input.v2_dim), layer_attr)


def pad_layer(input, pad_c=None, pad_h=None, pad_w=None, num_channels=None,
              name=None, layer_attr=None):
    """Zero-pad channel/height/width of an NCHW image (reference
    layers.py pad_layer)."""
    with cfg.build():
        img, _c = v2_layer._as_image(input, num_channels)
        pads = [0, 0] + list(pad_c or [0, 0]) + list(pad_h or [0, 0]) + \
            list(pad_w or [0, 0])
        var = fl.pad(img, paddings=pads)
    return _apply_extra(_wrap1(input, var), layer_attr)


def crop_layer(input, offset, axis=2, shape=None, name=None,
               layer_attr=None):
    with cfg.build():
        ref = input[1].var if isinstance(input, (list, tuple)) else None
        x = input[0].var if isinstance(input, (list, tuple)) else input.var
        full_off = [0] * axis + list(offset)
        var = fl.crop(x, shape=shape or ref, offsets=full_off)
    src = input[0] if isinstance(input, (list, tuple)) else input
    return _apply_extra(_wrap1(src, var), layer_attr)


def rotate_layer(input, height, width, name=None, layer_attr=None):
    """Rotate each HxW map 90 degrees counter-clockwise (reference
    layers.py rotate_layer)."""
    with cfg.build():
        x = fl.reshape(input.var, shape=[0, -1, height, width])
        var = fl.reshape(fl.reverse(fl.transpose(x, perm=[0, 1, 3, 2]),
                                    axis=[2]), shape=[0, -1])
    return _apply_extra(_wrap1(input, var, input.v2_dim), layer_attr)


def switch_order_layer(input, name=None, reshape_order=None,
                       layer_attr=None):
    with cfg.build():
        var = fl.transpose(input.var, perm=list(reshape_order))
    return _apply_extra(_wrap1(input, var), layer_attr)


def resize_layer(input, size, name=None, layer_attr=None):
    with cfg.build():
        var = fl.reshape(input.var, shape=[-1, int(size)])
    return _apply_extra(_wrap1(input, var, int(size)), layer_attr)


def repeat_layer(input, num_repeats, as_row_vector=True, act=None,
                 name=None, layer_attr=None):
    """Tile features num_repeats times (reference layers.py
    repeat_layer): row-vector mode yields [a b a b], column mode
    [a a b b]."""
    with cfg.build():
        var = input.var
        if as_row_vector:
            var = fl.reshape(
                fl.expand(fl.unsqueeze(var, axes=[1]),
                          expand_times=[1, num_repeats, 1]),
                shape=[0, -1])
        else:
            var = fl.reshape(
                fl.expand(fl.unsqueeze(var, axes=[2]),
                          expand_times=[1, 1, num_repeats]),
                shape=[0, -1])
        if act is not None:
            var = getattr(fl, act_name(act))(var)
    dim = input.v2_dim * num_repeats if input.v2_dim else None
    return _apply_extra(_wrap1(input, var, dim), layer_attr)


def upsample_layer(input, scale=2, num_channels=None, upsample_size=None,
                   name=None, layer_attr=None, **kwargs):
    with cfg.build():
        img, _c = v2_layer._as_image(input, num_channels)
        if upsample_size is not None:
            var = fl.image_resize(img, out_shape=upsample_size,
                                  resample="NEAREST")
        else:
            var = fl.image_resize(img, scale=scale, resample="NEAREST")
    return _apply_extra(_wrap1(input, var), layer_attr)


def bilinear_interp_layer(input, out_size_x=None, out_size_y=None,
                          num_channels=None, name=None, layer_attr=None):
    with cfg.build():
        img, _c = v2_layer._as_image(input, num_channels)
        var = fl.resize_bilinear(img, out_shape=[out_size_y, out_size_x])
    return _apply_extra(_wrap1(input, var), layer_attr)


def interpolation_layer(input, weight, name=None, layer_attr=None):
    """w*x1 + (1-w)*x2 with per-sample scalar w (reference layers.py
    interpolation_layer; input = [x1, x2])."""
    x1, x2 = input
    with cfg.build():
        w = weight.var
        one = fl.fill_constant(shape=[1], dtype=w.dtype, value=1.0)
        var = fl.elementwise_add(
            fl.elementwise_mul(x1.var, w),
            fl.elementwise_mul(x2.var, fl.elementwise_sub(one, w)))
    return _apply_extra(cfg.Layer(var, v2_dim=x1.v2_dim,
                                  parents=[x1, x2, weight]), layer_attr)


def linear_comb_layer(weights, vectors, size=None, name=None,
                      layer_attr=None):
    """Per-sample weighted sum of M size-d vectors: weights [B, M],
    vectors [B, M*d] -> [B, d] (reference layers.py linear_comb_layer)."""
    with cfg.build():
        m = int(weights.var.shape[-1])
        v3 = fl.reshape(vectors.var, shape=[0, m, -1])
        w3 = fl.unsqueeze(weights.var, axes=[1])          # [B, 1, M]
        var = fl.reshape(fl.matmul(w3, v3), shape=[0, -1])
    return _apply_extra(cfg.Layer(var, v2_dim=size,
                                  parents=[weights, vectors]), layer_attr)


convex_comb_layer = linear_comb_layer


def out_prod_layer(input1, input2, name=None, layer_attr=None):
    """Per-sample outer product -> [B, n1*n2] (reference layers.py
    out_prod_layer)."""
    with cfg.build():
        a = fl.unsqueeze(input1.var, axes=[2])            # [B, n1, 1]
        b = fl.unsqueeze(input2.var, axes=[1])            # [B, 1, n2]
        var = fl.reshape(fl.matmul(a, b), shape=[0, -1])
    return _apply_extra(cfg.Layer(var, parents=[input1, input2]),
                        layer_attr)


def tensor_layer(a, b, size, act=None, name=None, param_attr=None,
                 bias_attr=None, layer_attr=None):
    with cfg.build():
        var = fl.bilinear_tensor_product(
            a.var, b.var, size=size, act=act_name(act),
            param_attr=param_attr, bias_attr=bias_attr)
    return _apply_extra(cfg.Layer(var, v2_dim=size, parents=[a, b]),
                        layer_attr)


def scale_shift_layer(input, name=None, param_attr=None, bias_attr=None,
                      layer_attr=None):
    """Learned scalar w and shift b: y = w*x + b (reference layers.py
    scale_shift_layer)."""
    with cfg.build():
        var = input.var
        helper = LayerHelper("scale_shift", param_attr=param_attr,
                             bias_attr=bias_attr)
        w = helper.create_parameter(attr=helper.param_attr, shape=[1],
                                    dtype=var.dtype)
        var = fl.elementwise_mul(var, w)
        if bias_attr is not False:
            bvar = helper.create_parameter(attr=helper.bias_attr,
                                           shape=[1], dtype=var.dtype,
                                           is_bias=True)
            var = fl.elementwise_add(var, bvar)
    return _apply_extra(_wrap1(input, var, input.v2_dim), layer_attr)


def scale_sub_region_layer(input, indices, value, name=None,
                           layer_attr=None):
    with cfg.build():
        helper = LayerHelper("scale_sub_region")
        out = helper.create_variable_for_type_inference(input.var.dtype)
        helper.append_op(
            type="scale_sub_region",
            inputs={"X": [input.var], "Indices": [indices.var]},
            outputs={"Out": [out]}, attrs={"value": float(value)})
    return _apply_extra(cfg.Layer(out, parents=[input, indices]),
                        layer_attr)


def sum_to_one_norm_layer(input, name=None, layer_attr=None):
    with cfg.build():
        s = fl.reduce_sum(input.var, dim=-1, keep_dim=True)
        var = fl.elementwise_div(input.var, s)
    return _apply_extra(_wrap1(input, var, input.v2_dim), layer_attr)


def row_l2_norm_layer(input, name=None, layer_attr=None):
    with cfg.build():
        var = fl.l2_normalize(input.var, axis=-1)
    return _apply_extra(_wrap1(input, var, input.v2_dim), layer_attr)


def l2_distance_layer(x, y, name=None, layer_attr=None):
    with cfg.build():
        d = fl.elementwise_sub(x.var, y.var)
        var = fl.sqrt(fl.reduce_sum(fl.elementwise_mul(d, d), dim=-1,
                                    keep_dim=True))
    return _apply_extra(cfg.Layer(var, v2_dim=1, parents=[x, y]),
                        layer_attr)


def multiplex_layer(input, name=None, layer_attr=None):
    """First input is the per-row selector index; the rest are the
    candidates (reference layers.py multiplex_layer)."""
    with cfg.build():
        idx = fl.cast(input[0].var, "int32")
        var = fl.multiplex([l.var for l in input[1:]], idx)
    return _apply_extra(cfg.Layer(var, v2_dim=input[1].v2_dim,
                                  parents=list(input)), layer_attr)


def eos_layer(input, eos_id, name=None, layer_attr=None):
    """1.0 where the id equals eos_id else 0.0 (reference layers.py
    eos_layer's selection mask on this stack)."""
    with cfg.build():
        eos = fl.fill_constant_batch_size_like(
            input.var, shape=[-1, 1], dtype="int64", value=float(eos_id))
        var = fl.cast(fl.equal(fl.cast(input.var, "int64"), eos),
                      "float32")
    return _apply_extra(_wrap1(input, var, 1), layer_attr)


def sampling_id_layer(input, name=None, layer_attr=None):
    with cfg.build():
        var = fl.sampling_id(input.var)
    return _apply_extra(_wrap1(input, var, 1), layer_attr)


def print_layer(input, format=None, name=None):
    """In-graph print of the inputs; passes the first through
    (reference layers.py print_layer / printer_layer)."""
    if not isinstance(input, (list, tuple)):
        input = [input]
    with cfg.build():
        outs = [fl.Print(l.var, message=format or "") for l in input]
    return cfg.Layer(outs[0], v2_dim=input[0].v2_dim,
                     parents=list(input))


printer_layer = print_layer


# ---- image family ---------------------------------------------------------

def img_cmrnorm_layer(input, size, scale=0.0128, power=0.75,
                      num_channels=None, name=None, layer_attr=None):
    """Cross-map response normalization -> LRN (reference layers.py
    img_cmrnorm_layer; scale is the v1 alpha*size parameterization)."""
    with cfg.build():
        img, _c = v2_layer._as_image(input, num_channels)
        # v1 parameterizes scale = alpha * size (ProjectionConfig);
        # the lrn op wants alpha itself
        var = fl.lrn(img, n=size, alpha=float(scale) / size,
                     beta=float(power))
    return _apply_extra(_wrap1(input, var), layer_attr)


def cross_channel_norm_layer(input, name=None, param_attr=None,
                             layer_attr=None):
    """L2-normalize across channels with a learned per-channel scale
    (reference layers.py cross_channel_norm_layer — the SSD norm)."""
    with cfg.build():
        img, c = v2_layer._as_image(input, None)
        normed = fl.l2_normalize(img, axis=1)
        helper = LayerHelper("cross_channel_norm", param_attr=param_attr)
        w = helper.create_parameter(attr=helper.param_attr, shape=[c],
                                    dtype=img.dtype)
        var = fl.elementwise_mul(normed, fl.reshape(w, shape=[1, c, 1, 1]))
    return _apply_extra(_wrap1(input, var), layer_attr)


def spp_layer(input, name=None, num_channels=None, pool_type=None,
              pyramid_height=None, layer_attr=None):
    with cfg.build():
        img, _c = v2_layer._as_image(input, num_channels)
        helper = LayerHelper("spp")
        out = helper.create_variable_for_type_inference(img.dtype)
        ptype = "max"
        if pool_type is not None and \
                type(pool_type).__name__.lower().startswith("avg"):
            ptype = "avg"
        helper.append_op(
            type="spp", inputs={"X": [img]}, outputs={"Out": [out]},
            attrs={"pyramid_height": int(pyramid_height or 2),
                   "pooling_type": ptype})
    return _apply_extra(_wrap1(input, out), layer_attr)


def img_conv3d_layer(input, filter_size, num_filters, num_channels=None,
                     stride=1, padding=0, act=None, param_attr=None,
                     bias_attr=None, groups=1, name=None, layer_attr=None):
    with cfg.build():
        var = fl.conv3d(input.var, num_filters=num_filters,
                        filter_size=filter_size, stride=stride,
                        padding=padding, groups=groups,
                        act=act_name(act), param_attr=param_attr,
                        bias_attr=bias_attr)
    return _apply_extra(_wrap1(input, var), layer_attr)


def img_pool3d_layer(input, pool_size, num_channels=None, pool_type=None,
                     stride=1, padding=0, name=None, layer_attr=None):
    with cfg.build():
        ptype = "max"
        if pool_type is not None and \
                type(pool_type).__name__.lower().startswith("avg"):
            ptype = "avg"
        var = fl.pool3d(input.var, pool_size=pool_size, pool_type=ptype,
                        pool_stride=stride, pool_padding=padding)
    return _apply_extra(_wrap1(input, var), layer_attr)


def block_expand_layer(input, block_x=1, block_y=1, stride_x=1, stride_y=1,
                       padding_x=0, padding_y=0, num_channels=None,
                       name=None, layer_attr=None):
    """Image -> sequence of flattened blocks (reference layers.py
    block_expand_layer -> im2sequence_op)."""
    with cfg.build():
        img, _c = v2_layer._as_image(input, num_channels)
        var = fl.im2sequence(
            img, filter_size=[block_y, block_x],
            stride=[stride_y, stride_x],
            padding=[padding_y, padding_x, padding_y, padding_x])
    return _apply_extra(_wrap1(input, var), layer_attr)


def priorbox_layer(input, image, aspect_ratio, variance, min_size,
                   max_size=[], name=None):
    with cfg.build():
        img, _ = v2_layer._as_image(image, None)
        feat, _ = v2_layer._as_image(input, None)
        boxes, vars_ = fl.prior_box(
            feat, img, min_sizes=list(min_size),
            max_sizes=list(max_size), aspect_ratios=list(aspect_ratio),
            variance=list(variance), flip=True)
        var = fl.concat([fl.reshape(boxes, shape=[-1, 4]),
                         fl.reshape(vars_, shape=[-1, 4])], axis=0)
    return cfg.Layer(var, parents=[input, image])


def detection_output_layer(input_loc, input_conf, priorbox, num_classes,
                           nms_threshold=0.45, nms_top_k=400,
                           keep_top_k=200, confidence_threshold=0.01,
                           background_id=0, name=None):
    """SSD decode+NMS (reference layers.py detection_output_layer ->
    fluid detection_output)."""
    locs = input_loc if isinstance(input_loc, (list, tuple)) \
        else [input_loc]
    confs = input_conf if isinstance(input_conf, (list, tuple)) \
        else [input_conf]
    with cfg.build():
        loc = locs[0].var if len(locs) == 1 else \
            fl.concat([l.var for l in locs], axis=1)
        conf = confs[0].var if len(confs) == 1 else \
            fl.concat([c.var for c in confs], axis=1)
        pb = priorbox.var
        half = int(pb.shape[0]) // 2 if pb.shape[0] and pb.shape[0] > 0 \
            else None
        if half is None:
            raise ValueError("priorbox layer must have a static size")
        boxes = fl.slice(pb, axes=[0], starts=[0], ends=[half])
        pvar = fl.slice(pb, axes=[0], starts=[half], ends=[2 * half])
        decoded = fl.box_coder(boxes, pvar, loc,
                               code_type="decode_center_size")
        scores = fl.transpose(conf, perm=[0, 2, 1])   # [B, C, P]
        var = fl.multiclass_nms(
            decoded, scores, background_label=background_id,
            nms_threshold=nms_threshold, nms_top_k=nms_top_k,
            keep_top_k=keep_top_k, score_threshold=confidence_threshold)
    return cfg.Layer(var, parents=list(locs) + list(confs) + [priorbox])


def multibox_loss_layer(input_loc, input_conf, priorbox, label,
                        num_classes, overlap_threshold=0.5,
                        neg_pos_ratio=3.0, neg_overlap=0.5,
                        background_id=0, name=None, max_gt_boxes=None):
    """SSD training loss (reference layers.py multibox_loss_layer ->
    fluid ssd_loss).  ``label`` carries [label, xmin, ymin, xmax, ymax]
    rows per sample.  ``max_gt_boxes`` pins the static ground-truth
    count when the label is a variable-length sequence (the matching
    math needs static shapes under XLA)."""
    locs = input_loc if isinstance(input_loc, (list, tuple)) \
        else [input_loc]
    confs = input_conf if isinstance(input_conf, (list, tuple)) \
        else [input_conf]
    with cfg.build():
        loc = locs[0].var if len(locs) == 1 else \
            fl.concat([l.var for l in locs], axis=1)
        conf = confs[0].var if len(confs) == 1 else \
            fl.concat([c.var for c in confs], axis=1)
        pb = priorbox.var
        half = int(pb.shape[0]) // 2
        boxes = fl.slice(pb, axes=[0], starts=[0], ends=[half])
        pvar = fl.slice(pb, axes=[0], starts=[half], ends=[2 * half])
        gt = label.var
        if gt.shape[1] is None or gt.shape[1] < 0:
            if max_gt_boxes is None:
                raise ValueError(
                    "multibox_loss_layer: the label sequence length is "
                    "unknown at build time; pass max_gt_boxes= (the "
                    "padded ground-truth count) so the matching math "
                    "gets static shapes")
            gt = fl.reshape(gt, shape=[0, int(max_gt_boxes),
                                       int(gt.shape[-1])])
        gt_label = fl.cast(
            fl.slice(gt, axes=[2], starts=[0], ends=[1]), "int64")
        gt_box = fl.slice(gt, axes=[2], starts=[1], ends=[5])
        var = fl.ssd_loss(
            loc, conf, gt_box, gt_label, boxes, pvar,
            background_label=background_id,
            overlap_threshold=overlap_threshold,
            neg_pos_ratio=neg_pos_ratio, neg_overlap=neg_overlap)
        var = fl.reduce_sum(var)
    return cfg.Layer(var, parents=list(locs) + list(confs) +
                     [priorbox, label])


def roi_pool_layer(input, rois, pooled_width, pooled_height,
                   spatial_scale, num_channels=None, name=None):
    with cfg.build():
        img, _c = v2_layer._as_image(input, num_channels)
        var = fl.roi_pool(img, rois.var, pooled_height=pooled_height,
                          pooled_width=pooled_width,
                          spatial_scale=spatial_scale)
    return cfg.Layer(var, parents=[input, rois])


# ---- sequence family ------------------------------------------------------

def seq_concat_layer(a, b, act=None, name=None, layer_attr=None,
                     bias_attr=None):
    with cfg.build():
        var = fl.sequence_concat([a.var, b.var])
        if act is not None:
            var = getattr(fl, act_name(act))(var)
    return _apply_extra(cfg.Layer(var, v2_dim=a.v2_dim, parents=[a, b]),
                        layer_attr)


def seq_reshape_layer(input, reshape_size, act=None, name=None,
                      layer_attr=None, bias_attr=None):
    with cfg.build():
        var = fl.sequence_reshape(input.var, new_dim=reshape_size)
        if act is not None:
            var = getattr(fl, act_name(act))(var)
    return _apply_extra(_wrap1(input, var, reshape_size), layer_attr)


def _seq_slice(input, offsets, sizes):
    """sequence_slice with the op's full input contract: Offset/Size
    default to whole-sequence values, Length is the @LEN companion."""
    helper = LayerHelper("seq_slice")
    length = None
    ln_name = getattr(input, "_seq_len_name", None)
    if ln_name:
        length = input.block._find_var_recursive(ln_name)
    if length is None:
        raise ValueError(
            "seq_slice needs a sequence input (with a @LEN companion)")
    if offsets is None:
        offsets = fl.fill_constant_batch_size_like(
            input, shape=[-1, 1], dtype="int32", value=0)
    if sizes is None:
        sizes = fl.cast(fl.reshape(length, shape=[-1, 1]), "int32")
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="sequence_slice",
        inputs={"X": [input], "Offset": [offsets], "Size": [sizes],
                "Length": [length]},
        outputs={"Out": [out], "OutLength": [out_len]})
    out._seq_len_name = out_len.name
    return out


def seq_slice_layer(input, starts, ends, name=None):
    """Per-sequence [start, end) slices (reference layers.py
    seq_slice_layer)."""
    with cfg.build():
        off = starts.var if starts is not None else None
        if ends is not None and starts is not None:
            size = fl.elementwise_sub(ends.var, starts.var)
        elif ends is not None:
            size = ends.var
        else:
            size = None
        var = _seq_slice(input.var, off, size)
    parents = [p for p in (input, starts, ends) if p is not None]
    return cfg.Layer(var, v2_dim=input.v2_dim, parents=parents)


def sub_seq_layer(input, offsets, sizes, act=None, bias_attr=None,
                  name=None):
    with cfg.build():
        var = _seq_slice(input.var, offsets.var, sizes.var)
        if act is not None:
            var = getattr(fl, act_name(act))(var)
    return cfg.Layer(var, v2_dim=input.v2_dim,
                     parents=[input, offsets, sizes])


def sub_nested_seq_layer(input, selected_indices, name=None):
    raise NotImplementedError(
        "nested sequences are flattened by the padded+@LEN design "
        "(SURVEY §5 one-level ruling); restructure as a flat sequence "
        "with explicit segment ids")


def kmax_seq_score_layer(input, name=None, beam_size=1):
    """Indices of the k highest per-step scores in each sequence
    (reference layers.py kmax_seq_score_layer)."""
    with cfg.build():
        scores = fl.reshape(input.var, shape=[0, -1])
        _vals, idx = fl.topk(scores, k=beam_size)
    return cfg.Layer(idx, v2_dim=beam_size, parents=[input])


def recurrent_layer(input, act=None, bias_attr=None, param_attr=None,
                    name=None, reverse=False, layer_attr=None):
    """Elman recurrence h_t = act(x_t + W h_{t-1}) over a padded
    sequence (reference layers.py recurrent_layer / legacy
    RecurrentLayer)."""
    with cfg.build():
        x = input.var                      # [B, T, D]
        d = int(x.shape[-1])
        if reverse:
            x = fl.reverse(x, axis=[1])
        drnn = fl.DynamicRNN()
        with drnn.block():
            x_t = drnn.step_input(x)
            h_pre = drnn.memory(shape=[d], value=0.0)
            helper = LayerHelper("recurrent", param_attr=param_attr,
                                 bias_attr=bias_attr)
            w = helper.create_parameter(attr=helper.param_attr,
                                        shape=[d, d], dtype=x_t.dtype)
            pre = fl.elementwise_add(x_t, fl.matmul(h_pre, w))
            if bias_attr is not False:
                b = helper.create_parameter(attr=helper.bias_attr,
                                            shape=[d], dtype=x_t.dtype,
                                            is_bias=True)
                pre = fl.elementwise_add(pre, b)
            h = getattr(fl, act_name(act) or "tanh")(pre)
            drnn.update_memory(h_pre, h)
            drnn.output(h)
        var = drnn()
        if reverse:
            var = fl.reverse(var, axis=[1])
    return _apply_extra(_wrap1(input, var, input.v2_dim), layer_attr)


def lstm_step_layer(input, state, size=None, act=None, name=None,
                    gate_act=None, state_act=None, bias_attr=None,
                    layer_attr=None):
    """One LSTM step on a pre-projected [B, 4H] input (reference
    layers.py lstm_step_layer).  Returns the hidden; the new cell rides
    ``layer.state``."""
    with cfg.build():
        helper = LayerHelper("lstm_step")
        h = helper.create_variable_for_type_inference(input.var.dtype)
        c = helper.create_variable_for_type_inference(input.var.dtype)
        helper.append_op(
            type="lstm_unit",
            inputs={"X": [input.var], "C_prev": [state.var]},
            outputs={"H": [h], "C": [c]}, attrs={"forget_bias": 0.0})
    out = cfg.Layer(h, v2_dim=size, parents=[input, state])
    out.state = cfg.Layer(c, v2_dim=size, parents=[out])
    return out


def gru_step_layer(input, output_mem, size=None, act=None, name=None,
                   gate_act=None, bias_attr=None, param_attr=None,
                   layer_attr=None):
    """One GRU step on a pre-projected [B, 3H] input (reference
    layers.py gru_step_layer)."""
    with cfg.build():
        sz = size or int(input.var.shape[-1]) // 3
        h, _rhp, _gate = fl.gru_unit(
            input.var, output_mem.var, sz * 3, param_attr=param_attr,
            bias_attr=bias_attr,
            activation=act_name(act) or "tanh",
            gate_activation=act_name(gate_act) or "sigmoid")
    return cfg.Layer(h, v2_dim=size, parents=[input, output_mem])


gru_step_naive_layer = gru_step_layer


def gated_unit_layer(input, size, act=None, name=None, gate_attr=None,
                     gate_param_attr=None, gate_bias_attr=None,
                     inproj_attr=None, inproj_param_attr=None,
                     inproj_bias_attr=None, layer_attr=None):
    """GLU: fc(act) * sigmoid(fc) (reference layers.py
    gated_unit_layer)."""
    with cfg.build():
        nfd = len(input.var.shape) - 1
        proj = fl.fc(input.var, size=size, act=act_name(act),
                     num_flatten_dims=nfd,
                     param_attr=inproj_param_attr,
                     bias_attr=inproj_bias_attr)
        gate = fl.fc(input.var, size=size, act="sigmoid",
                     num_flatten_dims=nfd,
                     param_attr=gate_param_attr,
                     bias_attr=gate_bias_attr)
        var = fl.elementwise_mul(proj, gate)
    return _apply_extra(_wrap1(input, var, size), layer_attr)


def selective_fc_layer(input, size, select=None, act=None, name=None,
                       param_attr=None, bias_attr=None, layer_attr=None,
                       **kwargs):
    """fc whose outputs are masked by ``select`` (reference layers.py
    selective_fc_layer; the reference's sparse evaluation is an
    inference shortcut XLA's dense matmul does not need)."""
    with cfg.build():
        var = fl.fc(input.var, size=size, act=act_name(act),
                    num_flatten_dims=len(input.var.shape) - 1,
                    param_attr=param_attr, bias_attr=bias_attr)
        if select is not None:
            var = fl.elementwise_mul(var, select.var)
    parents = [input] + ([select] if select is not None else [])
    return _apply_extra(cfg.Layer(var, v2_dim=size, parents=parents),
                        layer_attr)


def factorization_machine(input, factor_size, act=None, name=None,
                          param_attr=None, layer_attr=None):
    """Second-order FM term 0.5*sum((xV)^2 - (x^2)(V^2)) (reference
    layers.py factorization_machine)."""
    with cfg.build():
        x = input.var
        d = int(x.shape[-1])
        helper = LayerHelper("fm", param_attr=param_attr)
        v = helper.create_parameter(attr=helper.param_attr,
                                    shape=[d, factor_size], dtype=x.dtype)
        xv = fl.matmul(x, v)                               # [B, K]
        x2v2 = fl.matmul(fl.elementwise_mul(x, x),
                         fl.elementwise_mul(v, v))         # [B, K]
        diff = fl.elementwise_sub(fl.elementwise_mul(xv, xv), x2v2)
        var = fl.scale(fl.reduce_sum(diff, dim=-1, keep_dim=True), 0.5)
        if act is not None:
            var = getattr(fl, act_name(act))(var)
    return _apply_extra(_wrap1(input, var, 1), layer_attr)


# ---- cost layers ----------------------------------------------------------

def rank_cost(left, right, label, weight=None, name=None, coeff=1.0,
              layer_attr=None):
    with cfg.build():
        cost = fl.rank_loss(label.var, left.var, right.var)
        if weight is not None:
            cost = fl.elementwise_mul(cost, weight.var)
        cost = fl.mean(cost)
        if coeff != 1.0:
            cost = fl.scale(cost, scale=float(coeff))
    parents = [p for p in (left, right, label, weight) if p is not None]
    return cfg.Layer(cost, parents=parents)


def huber_regression_cost(input, label, name=None, delta=1.0, coeff=1.0,
                          layer_attr=None):
    with cfg.build():
        helper = LayerHelper("huber")
        out = helper.create_variable_for_type_inference(input.var.dtype)
        helper.append_op(
            type="huber_loss",
            inputs={"X": [input.var], "Y": [label.var]},
            outputs={"Out": [out]}, attrs={"delta": float(delta)})
        cost = fl.scale(fl.mean(out), scale=float(coeff))
    return cfg.Layer(cost, parents=[input, label])


def huber_classification_cost(input, label, name=None, coeff=1.0,
                              layer_attr=None):
    """Squared-hinge-style huber for binary labels in {0,1} (reference
    layers.py huber_classification_cost / modified huber)."""
    with cfg.build():
        helper = LayerHelper("huber_cls")
        inter = helper.create_variable_for_type_inference(input.var.dtype)
        out = helper.create_variable_for_type_inference(input.var.dtype)
        helper.append_op(
            type="modified_huber_loss",
            inputs={"X": [input.var], "Y": [label.var]},
            outputs={"IntermediateVal": [inter], "Out": [out]})
        cost = fl.scale(fl.mean(out), scale=float(coeff))
    return cfg.Layer(cost, parents=[input, label])


def cross_entropy_with_selfnorm(input, label, name=None, coeff=1.0,
                                softmax_selfnorm_alpha=0.1,
                                layer_attr=None):
    """CE plus alpha * mean(log(Z)^2) where Z is each row's probability
    mass — pushes unnormalized scorers toward self-normalization
    (reference layers.py cross_entropy_with_selfnorm)."""
    with cfg.build():
        ce = fl.mean(fl.cross_entropy(input.var, label.var))
        z = fl.reduce_sum(input.var, dim=-1, keep_dim=False)
        logz = fl.log(z)
        pen = fl.mean(fl.elementwise_mul(logz, logz))
        cost = fl.scale(
            fl.elementwise_add(
                ce, fl.scale(pen, scale=float(softmax_selfnorm_alpha))),
            scale=float(coeff))
    return cfg.Layer(cost, parents=[input, label])


def lambda_cost(input, score, name=None, NDCG_num=5, max_sort_size=-1,
                layer_attr=None):
    """LambdaRank listwise cost over a padded sequence of scores
    (reference layers.py lambda_cost; NDCG-weighted pairwise logistic
    loss — ops/loss.py lambda_cost).  ``max_sort_size`` is accepted for
    parity: the whole (padded) list participates, which matches
    max_sort_size=-1."""
    with cfg.build():
        helper = LayerHelper("lambda_cost")
        out = helper.create_variable_for_type_inference(input.var.dtype)
        inputs = {"Score": [input.var], "Rel": [score.var]}
        ln = getattr(input.var, "_seq_len_name", None)
        if ln:
            inputs["Length"] = [ln]
        helper.append_op(type="lambda_cost", inputs=inputs,
                         outputs={"Out": [out]},
                         attrs={"ndcg_num": int(NDCG_num)})
        cost = fl.mean(out)
    return cfg.Layer(cost, parents=[input, score])


def cross_entropy_over_beam(input, name=None):
    raise NotImplementedError(
        "cross_entropy_over_beam trains the v1 beam-search machinery "
        "(reference layers.py BeamInput); beam training on this stack "
        "goes through layers.beam_search + softmax_with_cross_entropy "
        "(tests/test_rnn_encoder_decoder.py)")


def conv_shift_layer(a, b, name=None, layer_attr=None):
    """Circular 1-D correlation of each row of a with the (odd-width)
    kernel rows of b (reference layers.py conv_shift_layer /
    conv_shift_op.cc)."""
    with cfg.build():
        helper = LayerHelper("conv_shift")
        out = helper.create_variable_for_type_inference(a.var.dtype)
        helper.append_op(type="conv_shift",
                         inputs={"X": [a.var], "Y": [b.var]},
                         outputs={"Out": [out]})
    return _apply_extra(cfg.Layer(out, v2_dim=a.v2_dim, parents=[a, b]),
                        layer_attr)


def row_conv_layer(input, context_len, act=None, name=None,
                   param_attr=None, layer_attr=None):
    """Lookahead row convolution over a padded sequence (reference
    layers.py row_conv_layer / row_conv_op.cc)."""
    with cfg.build():
        var = fl.row_conv(input.var, future_context_size=context_len,
                          param_attr=param_attr)
        if act is not None:
            var = getattr(fl, act_name(act))(var)
    return _apply_extra(_wrap1(input, var, input.v2_dim), layer_attr)
