"""v1 composite networks (reference
python/paddle/trainer_config_helpers/networks.py:1) plus the
``inputs()``/``outputs()`` config markers.

The composites delegate to the shared v2 network builders (one
implementation serves both dialects); ``outputs()`` records which layers
the parsed model exposes — the v1 proto's ``output_layer_names`` — on
the global v2 graph so ``config_parser_utils.parse_network_config`` can
report them.
"""

from ..v2 import config as cfg
from ..v2 import networks as v2_net

__all__ = [
    "sequence_conv_pool", "simple_img_conv_pool", "img_conv_group",
    "simple_lstm", "simple_gru", "bidirectional_lstm",
    "simple_attention", "dot_product_attention",
    "inputs", "outputs",
]

sequence_conv_pool = v2_net.sequence_conv_pool
simple_img_conv_pool = v2_net.simple_img_conv_pool
img_conv_group = v2_net.img_conv_group
simple_lstm = v2_net.simple_lstm
simple_gru = v2_net.simple_gru
bidirectional_lstm = v2_net.bidirectional_lstm
simple_attention = v2_net.simple_attention
dot_product_attention = v2_net.dot_product_attention


def _flatten(layers):
    out = []
    for l in layers:
        if isinstance(l, (list, tuple)):
            out.extend(_flatten(l))
        else:
            out.append(l)
    return out


def inputs(*layers):
    """Declare data-layer order (reference networks.py inputs).  The v2
    graph already records data layers in call order; this re-orders to
    the declared order so feeding matches the v1 config."""
    g = cfg.graph()
    declared = _flatten(layers)
    names = {l.name for l in declared}
    rest = [l for l in g.data_layers if l.name not in names]
    g.data_layers = declared + rest


def outputs(*layers):
    """Mark network outputs (reference networks.py outputs)."""
    g = cfg.graph()
    g.output_layers = _flatten(layers)
