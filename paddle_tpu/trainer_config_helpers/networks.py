"""v1 composite networks (reference
python/paddle/trainer_config_helpers/networks.py:1) plus the
``inputs()``/``outputs()`` config markers.

The composites delegate to the shared v2 network builders (one
implementation serves both dialects); ``outputs()`` records which layers
the parsed model exposes — the v1 proto's ``output_layer_names`` — on
the global v2 graph so ``config_parser_utils.parse_network_config`` can
report them.
"""

from ..v2 import config as cfg
from ..v2 import networks as v2_net
from .. import nets as fnets

__all__ = [
    "sequence_conv_pool", "simple_img_conv_pool", "img_conv_group",
    "simple_lstm", "simple_gru", "bidirectional_lstm",
    "simple_attention", "dot_product_attention",
    "inputs", "outputs",
    "text_conv_pool", "img_conv_bn_pool", "img_separable_conv",
    "small_vgg", "vgg_16_network", "simple_gru2", "gru_group",
    "gru_unit", "lstmemory_group", "lstmemory_unit",
    "bidirectional_gru", "multi_head_attention",
]

sequence_conv_pool = v2_net.sequence_conv_pool
simple_img_conv_pool = v2_net.simple_img_conv_pool
img_conv_group = v2_net.img_conv_group
simple_lstm = v2_net.simple_lstm
simple_gru = v2_net.simple_gru
bidirectional_lstm = v2_net.bidirectional_lstm
simple_attention = v2_net.simple_attention
dot_product_attention = v2_net.dot_product_attention


def _flatten(layers):
    out = []
    for l in layers:
        if isinstance(l, (list, tuple)):
            out.extend(_flatten(l))
        else:
            out.append(l)
    return out


def inputs(*layers):
    """Declare data-layer order (reference networks.py inputs).  The v2
    graph already records data layers in call order; this re-orders to
    the declared order so feeding matches the v1 config."""
    g = cfg.graph()
    declared = _flatten(layers)
    names = {l.name for l in declared}
    rest = [l for l in g.data_layers if l.name not in names]
    g.data_layers = declared + rest


def outputs(*layers):
    """Mark network outputs (reference networks.py outputs)."""
    g = cfg.graph()
    g.output_layers = _flatten(layers)


# ===========================================================================
# parity tail: the remaining reference networks.py composites
# ===========================================================================

from .. import layers as fl                              # noqa: E402
from ..v2 import layer as v2_layer                       # noqa: E402
from ..v2.activation import act_name                     # noqa: E402
from . import layers as v1                               # noqa: E402

text_conv_pool = v2_net.sequence_conv_pool


def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     num_channel=None, conv_stride=1, conv_padding=0,
                     conv_act=None, pool_stride=1, pool_type=None,
                     bn_param_attr=None, bn_bias_attr=None,
                     conv_param_attr=None, **kwargs):
    """conv -> batch_norm -> pool (reference networks.py
    img_conv_bn_pool)."""
    conv = v1.img_conv_layer(
        input, filter_size=filter_size, num_filters=num_filters,
        num_channels=num_channel, stride=conv_stride,
        padding=conv_padding, act=None, param_attr=conv_param_attr)
    bn = v1.batch_norm_layer(conv, act=conv_act,
                             param_attr=bn_param_attr,
                             bias_attr=bn_bias_attr)
    return v1.img_pool_layer(bn, pool_size=pool_size, stride=pool_stride,
                             pool_type=pool_type)


def img_separable_conv(input, num_channels, num_out_channels, filter_size,
                       stride=1, padding=0, depth_multiplier=1, act=None,
                       bias_attr=None, param_attr=None, shared_bias=True,
                       name=None, **kwargs):
    """Depthwise conv then pointwise 1x1 conv (reference networks.py
    img_separable_conv)."""
    with cfg.build():
        img, c = v2_layer._as_image(input, num_channels)
        depthwise = fl.conv2d(
            img, num_filters=c * depth_multiplier,
            filter_size=filter_size, stride=stride, padding=padding,
            groups=c, param_attr=param_attr, bias_attr=bias_attr)
        pointwise = fl.conv2d(
            depthwise, num_filters=num_out_channels, filter_size=1,
            act=act_name(act), param_attr=param_attr,
            bias_attr=bias_attr)
    return cfg.Layer(pointwise, parents=[input])


def small_vgg(input_image, num_channels, num_classes, **kwargs):
    """The cifar small-VGG (reference networks.py small_vgg: four
    conv groups of 2/2/3/3 layers at 64/128/256/512 filters, two
    fc+bn+dropout heads)."""
    with cfg.build():
        img, _c = v2_layer._as_image(input_image, num_channels)
        tmp = img
        for groups, filters in ((2, 64), (2, 128), (3, 256), (3, 512)):
            tmp = fnets.img_conv_group(
                input=tmp, conv_num_filter=[filters] * groups,
                pool_size=2, conv_padding=1, conv_filter_size=3,
                conv_act="relu", conv_with_batchnorm=True,
                pool_stride=2, pool_type="max")
        drop = fl.dropout(tmp, dropout_prob=0.5)
        fc1 = fl.fc(drop, size=512, act=None)
        bn = fl.batch_norm(fc1, act="relu")
        bn = fl.dropout(bn, dropout_prob=0.5)
        fc2 = fl.fc(bn, size=512, act=None)
        out = fl.fc(fc2, size=num_classes, act="softmax")
    return cfg.Layer(out, v2_dim=num_classes, parents=[input_image])


def vgg_16_network(input_image, num_channels, num_classes=1000, **kwargs):
    """VGG-16 (reference networks.py vgg_16_network: conv groups
    2/2/3/3/3 at 64..512 + two 4096 fc heads)."""
    with cfg.build():
        img, _c = v2_layer._as_image(input_image, num_channels)
        tmp = img
        for groups, filters in ((2, 64), (2, 128), (3, 256), (3, 512),
                                (3, 512)):
            tmp = fnets.img_conv_group(
                input=tmp, conv_num_filter=[filters] * groups,
                pool_size=2, conv_padding=1, conv_filter_size=3,
                conv_act="relu", pool_stride=2, pool_type="max")
        fc1 = fl.fc(tmp, size=4096, act="relu")
        fc1 = fl.dropout(fc1, dropout_prob=0.5)
        fc2 = fl.fc(fc1, size=4096, act="relu")
        fc2 = fl.dropout(fc2, dropout_prob=0.5)
        out = fl.fc(fc2, size=num_classes, act="softmax")
    return cfg.Layer(out, v2_dim=num_classes, parents=[input_image])


def simple_gru2(input, size, name=None, reverse=False, mixed_param_attr=None,
                mixed_bias_attr=None, gru_param_attr=None,
                gru_bias_attr=None, act=None, gate_act=None,
                **kwargs):
    """fc projection + grumemory (reference networks.py simple_gru2 —
    numerically the same recurrence as simple_gru with the projection
    spelled as a mixed layer)."""
    proj = v1.fc_layer(input, size=size * 3, act=None,
                       param_attr=mixed_param_attr,
                       bias_attr=mixed_bias_attr)
    return v1.grumemory(proj, size=size, reverse=reverse, act=act,
                        gate_act=gate_act, param_attr=gru_param_attr,
                        bias_attr=gru_bias_attr, name=name)


def gru_group(input, size, name=None, reverse=False, param_attr=None,
              bias_attr=None, act=None, gate_act=None, **kwargs):
    """Full-sequence GRU recurrence (reference networks.py gru_group:
    a recurrent_group around gru_step_layer; this stack's scan-based
    grumemory computes the identical sequence of hidden states)."""
    return v1.grumemory(input, size=size, reverse=reverse, act=act,
                        gate_act=gate_act, param_attr=param_attr,
                        bias_attr=bias_attr, name=name)


def gru_unit(input, size=None, name=None, gru_param_attr=None,
             gru_bias_attr=None, act=None, gate_act=None, **kwargs):
    """reference networks.py gru_unit is the per-step cell used inside
    recurrent_group; recurrence here is scan-based, so this returns the
    full hidden sequence of the same cell (see gru_group)."""
    size = size or int(input.var.shape[-1]) // 3
    return v1.grumemory(input, size=size, act=act, gate_act=gate_act,
                        param_attr=gru_param_attr,
                        bias_attr=gru_bias_attr, name=name)


def lstmemory_group(input, size=None, name=None, reverse=False,
                    param_attr=None, act=None, gate_act=None,
                    state_act=None, lstm_bias_attr=None, **kwargs):
    """Full-sequence LSTM recurrence (reference networks.py
    lstmemory_group; see gru_group for the scan ruling)."""
    return v1.lstmemory(input, size=size, reverse=reverse, act=act,
                        gate_act=gate_act, state_act=state_act,
                        param_attr=param_attr, bias_attr=lstm_bias_attr,
                        name=name)


def lstmemory_unit(input, size=None, name=None, param_attr=None,
                   act=None, gate_act=None, state_act=None,
                   lstm_bias_attr=None, **kwargs):
    """reference networks.py lstmemory_unit: per-step LSTM cell for
    recurrent_group; returns the full hidden sequence of the same cell
    here (see gru_unit)."""
    return v1.lstmemory(input, size=size, act=act, gate_act=gate_act,
                        state_act=state_act, param_attr=param_attr,
                        bias_attr=lstm_bias_attr, name=name)


def bidirectional_gru(input, size, name=None, return_seq=False,
                      fwd_mixed_param_attr=None, bwd_mixed_param_attr=None,
                      fwd_gru_param_attr=None, bwd_gru_param_attr=None,
                      **kwargs):
    """Forward + backward GRU, last-step concat (or full sequences with
    return_seq=True) — reference networks.py bidirectional_gru."""
    fwd_proj = v1.fc_layer(input, size=size * 3, act=None,
                           param_attr=fwd_mixed_param_attr)
    fwd = v1.grumemory(fwd_proj, size=size,
                       param_attr=fwd_gru_param_attr)
    bwd_proj = v1.fc_layer(input, size=size * 3, act=None,
                           param_attr=bwd_mixed_param_attr)
    bwd = v1.grumemory(bwd_proj, size=size, reverse=True,
                       param_attr=bwd_gru_param_attr)
    with cfg.build():
        if return_seq:
            var = fl.concat([fwd.var, bwd.var], axis=2)
        else:
            f_last = fl.sequence_pool(fwd.var, "last")
            b_first = fl.sequence_pool(bwd.var, "first")
            var = fl.concat([f_last, b_first], axis=1)
    return cfg.Layer(var, v2_dim=2 * size, parents=[fwd, bwd])


def multi_head_attention(query, key, value, key_proj_size, value_proj_size,
                         head_num, attention_type="dot", softmax_param_attr=None,
                         name=None, **kwargs):
    """Multi-head scaled-dot attention over padded sequences (reference
    networks.py multi_head_attention; 'dot' attention — the TPU path is
    nets.scaled_dot_product_attention on projected q/k/v)."""
    if attention_type not in ("dot", "dot-product attention"):
        raise NotImplementedError(
            "additive multi-head attention is served by "
            "nets.simple_attention; this composite implements the "
            "reference's dot form")
    with cfg.build():
        q = fl.fc(query.var, size=key_proj_size * head_num,
                  num_flatten_dims=2, bias_attr=False)
        k = fl.fc(key.var, size=key_proj_size * head_num,
                  num_flatten_dims=2, bias_attr=False)
        v = fl.fc(value.var, size=value_proj_size * head_num,
                  num_flatten_dims=2, bias_attr=False)
        var = fnets.scaled_dot_product_attention(q, k, v,
                                                 num_heads=head_num)
    return cfg.Layer(var, v2_dim=value_proj_size * head_num,
                     parents=[query, key, value])
