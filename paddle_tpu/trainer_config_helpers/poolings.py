"""v1 pooling objects (reference
python/paddle/trainer_config_helpers/poolings.py:1).  Aliases of the
canonical v2 pooling objects, plus the sqrt-scaled sum pooling the v1
DSL exposed for bag-of-words layers."""

from ..v2 import pooling as _pool

__all__ = ["BasePoolingType", "MaxPooling", "AvgPooling", "SumPooling",
           "CudnnMaxPooling", "CudnnAvgPooling", "SquareRootNPooling",
           "MaxWithIdPooling"]

BasePoolingType = _pool.BasePool
MaxPooling = _pool.Max
AvgPooling = _pool.Avg
SumPooling = _pool.Sum
CudnnMaxPooling = _pool.CudnnMax
CudnnAvgPooling = _pool.CudnnAvg


class SquareRootNPooling(_pool.BasePool):
    """sum / sqrt(len) sequence pooling (reference poolings.py
    SquareRootNPooling); maps to the sequence_pool "sqrt" pooltype."""
    seq_type = "sqrt"
    img_type = "avg"


class MaxWithIdPooling(_pool.BasePool):
    """Max pooling that also records argmax indices in the v1 engine;
    on this stack the indices are recomputed where needed (maxid),
    so it degrades to plain max pooling."""
    seq_type = "max"
    img_type = "max"
