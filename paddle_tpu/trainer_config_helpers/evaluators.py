"""v1 evaluator declarations (reference
python/paddle/trainer_config_helpers/evaluators.py:1).

Each registers a metric subgraph on the global v2 graph (the same
mechanism ``v2.evaluator`` uses); the trainer fetches and reports them
per batch.  Curated to the evaluators with in-graph metric ops on this
stack (ops/metric.py); the printer evaluators degrade to value_printer.
"""

from ..v2 import config as cfg
from ..v2 import evaluator as v2_eval

__all__ = [
    "classification_error_evaluator", "auc_evaluator",
    "value_printer_evaluator", "sum_evaluator", "column_sum_evaluator",
]

classification_error_evaluator = v2_eval.classification_error
auc_evaluator = v2_eval.auc
value_printer_evaluator = v2_eval.value_printer


def _register(name, default_prefix, build_fn):
    """Unnamed evaluators get a unique name (the reference wraps these
    in wrap_name_default) so two unnamed registrations coexist; an
    explicit name replaces a prior registration under that name."""
    from .. import unique_name
    with cfg.build() as g:
        s = build_fn()
        if name is None:
            name = unique_name.generate(default_prefix)
        else:
            g.evaluators = [e for e in g.evaluators if e[0] != name]
        g.evaluators.append((name, s, None))
    return s


def sum_evaluator(input, name=None, weight=None):
    """Sum of the input over the batch (reference evaluators.py
    sum_evaluator)."""
    from .. import layers as fl
    return _register(name, "sum_evaluator",
                     lambda: fl.reduce_sum(cfg.unwrap(input)))


def column_sum_evaluator(input, name=None, weight=None):
    """Per-column sums (reference evaluators.py column_sum_evaluator)."""
    from .. import layers as fl
    return _register(name, "column_sum_evaluator",
                     lambda: fl.reduce_sum(cfg.unwrap(input), dim=0))
