"""v1 evaluator declarations (reference
python/paddle/trainer_config_helpers/evaluators.py:1).

Each registers a metric subgraph on the global v2 graph (the same
mechanism ``v2.evaluator`` uses); the trainer fetches and reports them
per batch.  Curated to the evaluators with in-graph metric ops on this
stack (ops/metric.py); the printer evaluators degrade to value_printer.
"""

from ..v2 import config as cfg
from ..v2 import evaluator as v2_eval

__all__ = [
    "classification_error_evaluator", "auc_evaluator",
    "value_printer_evaluator", "sum_evaluator", "column_sum_evaluator",
]

classification_error_evaluator = v2_eval.classification_error
auc_evaluator = v2_eval.auc
value_printer_evaluator = v2_eval.value_printer


def sum_evaluator(input, name=None, weight=None):
    """Sum of the input over the batch (reference evaluators.py
    sum_evaluator)."""
    from .. import layers as fl
    name = name or "sum_evaluator"
    with cfg.build() as g:
        s = fl.reduce_sum(cfg.unwrap(input))
        g.evaluators = [e for e in g.evaluators if e[0] != name]
        g.evaluators.append((name, s, None))
    return s


def column_sum_evaluator(input, name=None, weight=None):
    """Per-column sums (reference evaluators.py column_sum_evaluator)."""
    from .. import layers as fl
    name = name or "column_sum_evaluator"
    with cfg.build() as g:
        s = fl.reduce_sum(cfg.unwrap(input), dim=0)
        g.evaluators = [e for e in g.evaluators if e[0] != name]
        g.evaluators.append((name, s, None))
    return s
