"""v1 evaluator declarations (reference
python/paddle/trainer_config_helpers/evaluators.py:1).

Each registers a metric subgraph on the global v2 graph (the same
mechanism ``v2.evaluator`` uses); the trainer fetches and reports them
per batch.  Curated to the evaluators with in-graph metric ops on this
stack (ops/metric.py); the printer evaluators degrade to value_printer.
"""

from ..v2 import config as cfg
from ..v2 import evaluator as v2_eval

__all__ = [
    "classification_error_evaluator", "auc_evaluator",
    "value_printer_evaluator", "sum_evaluator", "column_sum_evaluator",
    "chunk_evaluator", "ctc_error_evaluator",
    "precision_recall_evaluator",
    "evaluator_base", "pnpair_evaluator", "detection_map_evaluator",
    "gradient_printer_evaluator", "maxid_printer_evaluator",
    "maxframe_printer_evaluator", "seqtext_printer_evaluator",
    "classification_error_printer_evaluator",
]

classification_error_evaluator = v2_eval.classification_error
auc_evaluator = v2_eval.auc
value_printer_evaluator = v2_eval.value_printer


def _register(name, default_prefix, build_fn):
    """Unnamed evaluators get a unique name (the reference wraps these
    in wrap_name_default) so two unnamed registrations coexist; an
    explicit name replaces a prior registration under that name."""
    from .. import unique_name
    with cfg.build() as g:
        s = build_fn()
        if name is None:
            name = unique_name.generate(default_prefix)
        else:
            g.evaluators = [e for e in g.evaluators if e[0] != name]
        g.evaluators.append((name, s, None))
    return s


def sum_evaluator(input, name=None, weight=None):
    """Sum of the input over the batch (reference evaluators.py
    sum_evaluator)."""
    from .. import layers as fl
    return _register(name, "sum_evaluator",
                     lambda: fl.reduce_sum(cfg.unwrap(input)))


def column_sum_evaluator(input, name=None, weight=None):
    """Per-column sums (reference evaluators.py column_sum_evaluator)."""
    from .. import layers as fl
    return _register(name, "column_sum_evaluator",
                     lambda: fl.reduce_sum(cfg.unwrap(input), dim=0))


def chunk_evaluator(input, label, chunk_scheme, num_chunk_types,
                    name=None, excluded_chunk_types=None):
    """Chunk precision/recall/F1 over tag sequences (reference
    evaluators.py chunk_evaluator over the chunk_eval op; the SRL book
    chapter's metric).  Registers F1 as the reported value."""
    from .. import layers as fl
    return _register(name, "chunk_evaluator", lambda: fl.chunk_eval(
        cfg.unwrap(input), cfg.unwrap(label), chunk_scheme=chunk_scheme,
        num_chunk_types=num_chunk_types,
        excluded_chunk_types=excluded_chunk_types)[2])


def ctc_error_evaluator(input, label, name=None):
    """Mean normalized edit distance between the decoded prediction and
    the label sequence (reference evaluators.py ctc_error_evaluator over
    edit_distance)."""
    from .. import layers as fl

    def build():
        dist, _n = fl.edit_distance(cfg.unwrap(input), cfg.unwrap(label),
                                    normalized=True)
        return fl.mean(dist)

    return _register(name, "ctc_error_evaluator", build)


def precision_recall_evaluator(input, label, positive_label=None,
                               weight=None, name=None):
    """Per-batch top-1 accuracy as the iteration-reported metric
    (reference evaluators.py precision_recall_evaluator's role in the
    training loop); the full streaming precision/recall/F1 curve lives
    host-side in metrics.py Precision/Recall — the same in-graph vs
    python-metric split the reference draws."""
    from .. import layers as fl

    def build():
        # per-batch accuracy of the argmax against the label is the
        # stateless surrogate the v2 trainer can report each iteration;
        # the full streaming PR curve lives in metrics.py Precision/
        # Recall (host-side), matching the reference's split between
        # in-graph evaluators and python metrics
        return fl.accuracy(input=cfg.unwrap(input), label=cfg.unwrap(label))

    return _register(name, "precision_recall_evaluator", build)


# ---- parity tail: the remaining reference evaluators.py names -------------

def evaluator_base(input, type=None, label=None, weight=None, name=None,
                   **kwargs):
    """Low-level evaluator registration (reference evaluators.py
    evaluator_base).  Typed uses DISPATCH to the matching specific
    evaluator (ADVICE r4: silently reducing the input for e.g.
    type='classification_error' reported a meaningless number); unknown
    types raise instead of mis-reporting.  Untyped registration keeps
    the raw-sum behavior (the reference's base path)."""
    from .. import layers as fl

    if type:
        typed = {
            "classification_error": lambda:
                classification_error_evaluator(input, label, name=name),
            "last-column-auc": lambda:
                auc_evaluator(input, label, name=name),
            "sum": lambda: sum_evaluator(input, name=name, weight=weight),
            "last-column-sum": lambda:
                column_sum_evaluator(input, name=name, weight=weight),
            "ctc_edit_distance": lambda:
                ctc_error_evaluator(input, label, name=name),
            "precision_recall": lambda: precision_recall_evaluator(
                input, label, weight=weight, name=name),
            "value_printer": lambda:
                value_printer_evaluator(input, name=name),
        }.get(type)
        if typed is None:
            raise NotImplementedError(
                "evaluator_base type=%r has no dispatch here; use the "
                "specific *_evaluator helper (reference evaluators.py "
                "maps types onto the same helpers)" % type)
        return typed()
    return _register(name, "evaluator",
                     lambda: fl.reduce_sum(cfg.unwrap(input)))


def pnpair_evaluator(input, label, query_id, weight=None, name=None):
    """Positive-negative pair ratio for ranking (reference
    evaluators.py pnpair_evaluator over positive_negative_pair_op)."""
    from ..layer_helper import LayerHelper

    def build():
        helper = LayerHelper("pnpair")
        pos = helper.create_variable_for_type_inference("float32")
        neg = helper.create_variable_for_type_inference("float32")
        ratio = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            type="positive_negative_pair",
            inputs={"Score": [cfg.unwrap(input)],
                    "Label": [cfg.unwrap(label)],
                    "QueryID": [cfg.unwrap(query_id)]},
            outputs={"PositivePair": [pos], "NegativePair": [neg],
                     "NeutralPair": [ratio]})
        return pos
    return _register(name, "pnpair_evaluator", build)


def detection_map_evaluator(input, label, class_num,
                            overlap_threshold=0.5, background_id=0,
                            evaluate_difficult=False, ap_type="11point",
                            name=None, **kwargs):
    """Detection mAP (reference evaluators.py detection_map_evaluator);
    delegates to the fluid detection_map layer (which wires the count
    companions the op needs).  ``class_num`` is required — the op sizes
    its per-class accumulators with it."""
    from .. import layers as fl

    def build():
        return fl.detection_map(
            cfg.unwrap(input), cfg.unwrap(label), class_num=class_num,
            background_label=int(background_id),
            overlap_threshold=float(overlap_threshold),
            evaluate_difficult=bool(evaluate_difficult),
            ap_version="11point" if ap_type == "11point" else "integral")
    return _register(name, "detection_map_evaluator", build)


def _printer(default_prefix):
    """The printer evaluators (reference evaluators.py *_printer_*):
    their capability — dump values during evaluation — maps onto the
    in-graph Print op feeding a value_printer registration."""
    def make(input, name=None, **kwargs):
        from .. import layers as fl

        def build():
            vars_ = input if isinstance(input, (list, tuple)) else [input]
            outs = [fl.Print(cfg.unwrap(v), message=default_prefix)
                    for v in vars_]
            return outs[0]
        return _register(name, default_prefix, build)
    return make


gradient_printer_evaluator = _printer("gradient_printer")
maxid_printer_evaluator = _printer("maxid_printer")
maxframe_printer_evaluator = _printer("maxframe_printer")
seqtext_printer_evaluator = _printer("seqtext_printer")
classification_error_printer_evaluator = _printer(
    "classification_error_printer")
