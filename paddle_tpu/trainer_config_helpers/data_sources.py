"""v1 data-source declarations (reference
python/paddle/trainer_config_helpers/data_sources.py:1).

``define_py_data_sources2`` bound a PyDataProvider2 module to the
trainer binary.  On this stack data flows through host-side readers
(``paddle_tpu.reader``) — the declaration is recorded so
``resolve_provider`` can import the module and hand back the generator
functions, which a training loop feeds through ``DataFeeder`` exactly
like any other reader.
"""

import importlib

__all__ = ["define_py_data_sources2", "current_data_sources",
           "resolve_provider", "reset_data_sources"]


class DataSourceSpec(object):
    def __init__(self, file_list, module, obj, args):
        self.file_list = file_list
        self.module = module
        self.obj = obj
        self.args = args


_sources = {}


def define_py_data_sources2(train_list, test_list, module, obj, args=None):
    """Record the train/test provider bindings (reference
    data_sources.py define_py_data_sources2).  ``obj`` may differ per
    split via a dict {"train": ..., "test": ...} as in v1."""

    def _split(v, split):
        # obj and args may each be a {"train": ..., "test": ...} dict
        if isinstance(v, dict) and set(v) <= {"train", "test"} and v:
            return v[split]
        return v

    global _sources
    if train_list is not None:
        _sources["train"] = DataSourceSpec(
            train_list, module, _split(obj, "train"), _split(args, "train"))
    if test_list is not None:
        _sources["test"] = DataSourceSpec(
            test_list, module, _split(obj, "test"), _split(args, "test"))


def current_data_sources():
    return dict(_sources)


def reset_data_sources():
    global _sources
    _sources = {}


def resolve_provider(split="train"):
    """Import the declared provider and return ``fn(file_list, args)`` —
    expected to be a reader-style generator factory on this stack (the
    PyDataProvider2 decorator protocol is not re-implemented; providers
    written for this framework are plain readers)."""
    spec = _sources.get(split)
    if spec is None:
        raise KeyError("no %s data source declared" % split)
    mod = importlib.import_module(spec.module)
    fn = getattr(mod, spec.obj)
    return lambda: fn(spec.file_list, spec.args)
