"""Default-filling decorators (reference
python/paddle/trainer_config_helpers/default_decorators.py:1).

The v1 DSL wraps every layer in decorators that fill ``name``/
``param_attr``/``bias_attr``/``act`` defaults; user extension code
imports them to write custom layers.  Re-implemented generically: each
returns a decorator that replaces a None (or missing) keyword with the
default factory's value.
"""

import functools
import inspect

from .. import unique_name
from .activations import LinearActivation

__all__ = ["wrap_name_default", "wrap_param_attr_default",
           "wrap_bias_attr_default", "wrap_act_default",
           "wrap_param_default"]


def wrap_param_default(param_names, default_factory, **bound):
    """Fill each named keyword with default_factory(func) when the call
    passes None (reference default_decorators.py wrap_param_default)."""

    def decorator(func):
        sig = inspect.signature(func)

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            ba = sig.bind_partial(*args, **kwargs)
            for name in param_names:
                if ba.arguments.get(name) is None:
                    # fill through the bound arguments so a positional
                    # None is replaced too (not a duplicate kwarg)
                    ba.arguments[name] = default_factory(func)
            return func(*ba.args, **ba.kwargs)

        return wrapper

    return decorator


def wrap_name_default(name_prefix=None, name_param="name"):
    prefix = name_prefix or "layer"
    return wrap_param_default(
        [name_param], lambda func: unique_name.generate(prefix))


def wrap_param_attr_default(param_names=None, default_factory=None):
    names = param_names or ["param_attr"]
    factory = default_factory or (lambda func: None)
    return wrap_param_default(names, factory)


def wrap_bias_attr_default(param_names=None, default_factory=None,
                           has_bias=True):
    names = param_names or ["bias_attr"]
    factory = default_factory or (lambda func: has_bias)
    return wrap_param_default(names, factory)


def wrap_act_default(param_names=None, act=None):
    names = param_names or ["act"]
    default = act if act is not None else LinearActivation()
    return wrap_param_default(names, lambda func: default)
