"""Misc DSL helpers (reference
python/paddle/trainer_config_helpers/utils.py:1)."""

import functools
import warnings

__all__ = ["deprecated"]


def deprecated(instead=None):
    """Mark a config helper as deprecated, pointing at the replacement
    (the reference's deprecated_wrapper logs through the config
    parser)."""

    def decorator(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            msg = "%s is deprecated" % func.__name__
            if instead:
                msg += "; use %s instead" % instead
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        return wrapper

    return decorator
