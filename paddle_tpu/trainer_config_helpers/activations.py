"""v1 activation objects (reference
python/paddle/trainer_config_helpers/activations.py:1).

The v1 config DSL names activations ``<Kind>Activation``; the v2 API
re-exports the same classes under short names.  Here the relationship is
inverted — the v2 activation objects are the canonical ones (they map to
fluid-parity activation op types), and this module aliases them under
the v1 names so v1 configs run unchanged.
"""

from ..v2 import activation as _act

__all__ = [
    "BaseActivation", "TanhActivation", "SigmoidActivation",
    "SoftmaxActivation", "IdentityActivation", "LinearActivation",
    "ReluActivation", "BReluActivation", "SoftReluActivation",
    "STanhActivation", "AbsActivation", "SquareActivation",
    "ExpActivation", "LogActivation",
]

BaseActivation = _act.Base
TanhActivation = _act.Tanh
SigmoidActivation = _act.Sigmoid
SoftmaxActivation = _act.Softmax
IdentityActivation = _act.Identity
LinearActivation = _act.Linear
ReluActivation = _act.Relu
BReluActivation = _act.BRelu
SoftReluActivation = _act.SoftRelu
STanhActivation = _act.STanh
AbsActivation = _act.Abs
SquareActivation = _act.Square
ExpActivation = _act.Exp
LogActivation = _act.Log
