"""v1 trainer-config DSL dialect (reference
python/paddle/trainer_config_helpers/__init__.py:1).

The third API dialect served by the single TPU execution engine (after
the fluid-parity and v2 surfaces; README.md documents the fold): v1
configs — ``*_layer`` calls, ``mixed_layer`` projections, ``settings()``,
``outputs()`` — build the same Program IR everything else jit-compiles.
The legacy per-layer C++ engine they configured
(``legacy/gserver/gradientmachines/GradientMachine.h:75``) is the part
XLA replaces; the DSL itself is fully live, and composes with the v2
trainer (``paddle_tpu.v2.trainer.SGD``) for execution.
"""

from .activations import *  # noqa: F401,F403
from .attrs import *  # noqa: F401,F403
from .config_parser_utils import *  # noqa: F401,F403
from .data_sources import *  # noqa: F401,F403
from .default_decorators import *  # noqa: F401,F403
from .evaluators import *  # noqa: F401,F403
from . import layer_math  # noqa: F401 - installs LayerOutput operators
from .layers import *  # noqa: F401,F403
from .networks import *  # noqa: F401,F403
from .optimizers import *  # noqa: F401,F403
from .poolings import *  # noqa: F401,F403
