"""v1 attribute objects (reference
python/paddle/trainer_config_helpers/attrs.py:1).

``ParameterAttribute`` builds a fluid-parity ``ParamAttr`` through the
same kwarg mapping the v2 dialect uses (initial_mean/std -> Normal
initializer, l1/l2 rates -> regularizers, is_static -> trainable=False,
sparse_update -> SelectedRows sparse-grad flag).  ``ExtraLayerAttribute``
carries the layer-level extras; only ``drop_rate`` and
``error_clipping_threshold`` are meaningful on this stack — the rest of
the v1 fields were GPU scheduling hints absorbed by XLA.
"""

from ..v2.attr import ExtraAttr as _ExtraAttr
from ..v2.attr import ParamAttr as _v2_param_attr

__all__ = ["ParameterAttribute", "ExtraLayerAttribute",
           "ParamAttr", "ExtraAttr"]


def ParameterAttribute(name=None, is_static=False, initial_std=None,
                       initial_mean=None, initial_max=None, initial_min=None,
                       l1_rate=None, l2_rate=None, learning_rate=None,
                       momentum=None, gradient_clipping_threshold=None,
                       sparse_update=False, update_hooks=None,
                       initializer=None):
    """reference attrs.py ParameterAttribute.  initial_min/max select a
    Uniform initializer (the v1 default was uniform over +-initial_std)."""
    if initializer is None and initial_max is not None:
        from .. import initializer as init_mod
        lo = initial_min if initial_min is not None else -initial_max
        initializer = init_mod.UniformInitializer(low=lo, high=initial_max)
    return _v2_param_attr(
        name=name, initial_std=initial_std, initial_mean=initial_mean,
        is_static=is_static, l1_rate=l1_rate, l2_rate=l2_rate,
        learning_rate=learning_rate, momentum=momentum,
        gradient_clipping_threshold=gradient_clipping_threshold,
        sparse_update=sparse_update, initializer=initializer)


ExtraLayerAttribute = _ExtraAttr
ParamAttr = ParameterAttribute
ExtraAttr = _ExtraAttr
