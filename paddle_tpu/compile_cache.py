"""Compilation caching: persistent XLA cache + program-fingerprint trace cache.

Two layers, addressing the two costs a repeated step shape pays:

* **Persistent XLA compilation cache** (``enable_persistent_cache``): the
  jax/XLA on-disk executable cache, keyed by HLO fingerprint.  Survives
  process restarts — bench-ladder rungs, test runs, and training restarts
  with the same program+signature skip XLA's optimization pipeline and
  deserialize the executable instead.  Wired to ``FLAGS_compile_cache_dir``
  (env ``FLAGS_compile_cache_dir=/path`` enables it before the first jit).
* **Process-global trace cache** (``lookup``/``store``): re-tracing is a
  host-side cost the XLA cache cannot amortize (jaxpr building walks every
  op's compute function).  Executors cache their jitted step callables here
  keyed by a *structural* program fingerprint, so a second Executor /
  ParallelExecutor instance over the same program (bench reruns inside one
  process, evaluator clones, tests) reuses the traced+jitted callable and
  performs zero lowerings.

``stats()`` exposes hit/miss/lowering counters; the executors emit
``compile_cache/hit`` / ``compile_cache/miss`` profiler marks at every
lookup so cache behavior is visible in the chrome trace next to the
``trace``/``compile``/``dispatch`` spans.
"""

import collections
import hashlib
import os
import threading

from .profiler import mark_event

__all__ = [
    "program_fingerprint", "trace_key", "trace_flag_values", "lookup",
    "store", "stats", "reset_stats", "clear", "enable_persistent_cache",
    "rescope_persistent_cache",
]


def trace_flag_values():
    """Values of every FLAGS_* knob that alters the traced jaxpr (kernel
    selection, BN variance form, flash-attention seq cutoff).  Every key
    under which a trace/compiled step is cached — the executors' per-
    instance keys AND the trace-cache keys here — must include this
    tuple, or set_flags between runs serves a stale trace."""
    from . import autotune, flags

    from . import guardian
    from .monitor import health

    # the guardian's in-graph skip guard wraps the traced step (extra
    # ok fetch + state selects), so its enablement is part of the jaxpr
    # identity: flipping FLAGS_guardian re-lowers instead of serving an
    # unguarded (or guarded) stale trace.  Same for the health probe
    # (extra grad fetches + the stats reduction); its CADENCE is host-
    # side publication only and deliberately not keyed.  The autotune
    # trace token carries the attention decision table's content: a
    # tuned kernel ruling is baked into the lowered step the same way
    # the flags are, so a changed ruling must re-lower too.
    return (flags.flag("pallas_kernels"), flags.flag("bn_two_pass"),
            flags.flag("pallas_attention_max_seq"),
            guardian.skip_guard_enabled(), health.probe_enabled(),
            autotune.trace_token())

_mu = threading.Lock()
# LRU of jitted step entries: the jitted callables keep their traced
# programs alive, so the cache is bounded (a bench ladder lowers dozens
# of programs, not thousands)
_MAX_ENTRIES = 64
_TRACE_CACHE = collections.OrderedDict()
_STATS = {"trace_hits": 0, "trace_misses": 0, "lowerings": 0}
# lowering counts per short program fingerprint: a retrace storm in the
# stats/StepStats names WHICH program is churning, not just that one is
_LOWERINGS_BY_FP = {}
_persistent_dir = [None]
_persistent_base = [None]     # user-given dir, before any world scoping


# ---------------------------------------------------------------------------
# program fingerprint
# ---------------------------------------------------------------------------

def program_fingerprint(program):
    """Stable structural digest of a Program: every block's ops (type,
    slot bindings, attrs) and vars (shape/dtype/persistability), plus the
    seed and AMP policy.  Cached on the program keyed by ``_version`` so
    the per-step cost is one attribute read; structural mutation (op
    append/insert, rename) bumps ``_version`` and re-hashes."""
    # memo key carries the AMP policy too: bf16_program_guard swaps
    # _amp_policy WITHOUT a structural mutation (no _version bump), and
    # serving the fp32 trace under the guard would silently drop AMP
    amp = getattr(program, "_amp_policy", None)
    memo_key = (program._version, None if amp is None else repr(amp))
    cached = getattr(program, "_fp_cache", None)
    if cached is not None and cached[0] == memo_key:
        return cached[1]
    h = hashlib.sha1()
    try:
        h.update(program.to_json().encode())
    except (TypeError, ValueError):
        # an op attr that doesn't serialize (sub-block handle, callable):
        # fall back to repr, which is stable within the process
        for blk in program.blocks:
            for op in blk.ops:
                h.update(repr((op.type, sorted(op.inputs.items()),
                               sorted(op.outputs.items()),
                               sorted((k, repr(v))
                                      for k, v in op.attrs.items()))
                              ).encode())
            for n, v in blk.vars.items():
                h.update(repr((n, v.shape, str(v.dtype), v.persistable,
                               v.lod_level)).encode())
        h.update(repr(program.random_seed).encode())
    if amp is not None:
        h.update(repr(amp).encode())
    fp = h.hexdigest()
    program._fp_cache = (memo_key, fp)
    return fp


def trace_key(program, feed_sig, state_sig, fetch_names, *extras):
    """Key for the process-global trace cache.  ``state_sig`` must carry
    the state names (the scope-dependent half of the lowering); ``extras``
    carries executor-specific trace-time choices (platform, donation,
    mesh/sharding identity, kernel-selection flags)."""
    return (program_fingerprint(program), tuple(feed_sig),
            tuple(state_sig), tuple(fetch_names)) + tuple(extras)


# ---------------------------------------------------------------------------
# trace cache
# ---------------------------------------------------------------------------

def lookup(key):
    with _mu:
        entry = _TRACE_CACHE.get(key)
        if entry is not None:
            _TRACE_CACHE.move_to_end(key)
            _STATS["trace_hits"] += 1
            mark_event("compile_cache/hit")
            return entry
        _STATS["trace_misses"] += 1
        mark_event("compile_cache/miss")
        return None


def store(key, entry):
    with _mu:
        _STATS["lowerings"] += 1
        if key and isinstance(key[0], str):
            fp12 = key[0][:12]   # trace_key leads with the fingerprint
            _LOWERINGS_BY_FP[fp12] = _LOWERINGS_BY_FP.get(fp12, 0) + 1
        _TRACE_CACHE[key] = entry
        _TRACE_CACHE.move_to_end(key)
        while len(_TRACE_CACHE) > _MAX_ENTRIES:
            _TRACE_CACHE.popitem(last=False)
    return entry


def stats():
    """Counters since process start (or the last ``reset_stats``).
    ``hit_ratio`` (hits / lookups, 0.0 before the first lookup) is the
    StepStats field: a warm steady-state loop sits at ~1.0 and a retrace
    storm (shape churn, program mutation) drags it visibly down.
    Per-lookup hit/miss marks additionally double-publish as
    ``mark/compile_cache/{hit,miss}`` monitor counters."""
    with _mu:
        out = dict(_STATS)
        out["lowerings_by_program"] = dict(_LOWERINGS_BY_FP)
    lookups = out["trace_hits"] + out["trace_misses"]
    out["hit_ratio"] = round(out["trace_hits"] / lookups, 4) if lookups \
        else 0.0
    out["entries"] = len(_TRACE_CACHE)
    out["persistent_dir"] = _persistent_dir[0]
    return out


def reset_stats():
    with _mu:
        for k in _STATS:
            _STATS[k] = 0
        _LOWERINGS_BY_FP.clear()


def clear():
    """Drop every cached trace (tests; frees the traced programs)."""
    with _mu:
        _TRACE_CACHE.clear()


# ---------------------------------------------------------------------------
# persistent XLA compilation cache
# ---------------------------------------------------------------------------

def _known_world_size():
    """The jax process count, WITHOUT initializing the backend: only
    consulted when ``parallel.distributed`` is already imported and
    reports the world joined (probing ``jax.process_count()`` directly
    would initialize the backend, which must not happen at flag-import
    time, before a later ``jax.distributed.initialize``)."""
    import sys

    dist = sys.modules.get("paddle_tpu.parallel.distributed")
    if dist is not None and dist.is_initialized():
        import jax

        return jax.process_count()
    return 1


def rescope_persistent_cache():
    """Re-point the persistent cache at a world-scoped subdirectory
    (``world_<N>``) once the process count is known — called by
    ``parallel.distributed.init_distributed`` AFTER the jax runtime
    joined the world (covering caches enabled BEFORE the join; caches
    enabled after it scope themselves in ``enable_persistent_cache``).
    Single-process runs keep the base directory, so an elastic-resume
    survivor restarts warm off the solo entries while never
    deserializing a multi-process executable: an N-process module
    embeds cross-process collective wiring and silently computes
    garbage in any other world shape (found by the cluster drill)."""
    base = _persistent_base[0]
    if base:
        enable_persistent_cache(base)


def enable_persistent_cache(cache_dir):
    """Point jax's on-disk executable cache at ``cache_dir`` (empty/None
    disables).  Thresholds are zeroed so even the CPU-backend test shapes
    cache: the bench ladder's win case is many small-to-medium modules
    recompiled across subprocess rungs and re-invocations.  In a
    multi-process world (already joined at call time, or joined later
    through ``init_distributed``) the cache lands in a ``world_<N>``
    subdirectory — see ``rescope_persistent_cache``."""
    import jax

    _persistent_base[0] = cache_dir or None
    if cache_dir:
        n = _known_world_size()
        if n > 1:
            cache_dir = os.path.join(cache_dir, "world_%d" % n)
    _persistent_dir[0] = cache_dir or None
    jax.config.update("jax_compilation_cache_dir", cache_dir or None)
    if not cache_dir:
        return
    for name, val in (
        ("jax_enable_compilation_cache", True),
        ("jax_persistent_cache_min_compile_time_secs", 0),
        ("jax_persistent_cache_min_entry_size_bytes", 0),
    ):
        try:
            jax.config.update(name, val)
        except AttributeError:
            # older/newer jax spelling; the dir alone still enables it
            pass
    try:
        # jax memoizes "cache disabled" on first compile: a process that
        # already jitted before the flag was set would silently never
        # cache.  reset_cache drops that memo so the new dir takes
        # effect immediately.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except (ImportError, AttributeError):
        pass
