"""Standalone op-spec construction from registry metadata (reference
python/paddle/fluid/op.py:1).

The reference converts keyword arguments to an ``OpDesc`` proto by
consulting the C++ ``OpProto`` registry (``get_all_op_protos``,
``OpDescCreationMethod``) — used by low-level tests and tools that build
ops outside the layer DSL.  Here the registry is ``registry.OPS``
(OpDef objects); ``Operator("scale", X="x", Out="y", scale=2.0)``
validates slots against the OpDef and returns the plain op-spec dict
``{"type", "inputs", "outputs", "attrs"}`` that ``Block.append_op``
accepts — the OpDesc analog on this stack.
"""

from . import registry

__all__ = ["get_all_op_protos", "Operator", "OpDescCreationMethod"]


def get_all_op_protos():
    """All registered OpDefs (reference op.py get_all_op_protos)."""
    return [registry.OPS[t] for t in sorted(registry.OPS)]


class OpDescCreationMethod(object):
    """kwargs -> op-spec dict for one op type (reference op.py
    OpDescCreationMethod; validation semantics preserved: unknown
    keywords are rejected, every kwarg must name an input slot, an
    output slot, or an attribute)."""

    def __init__(self, op_def):
        if not isinstance(op_def, registry.OpDef):
            raise TypeError("expected a registry.OpDef, got %r" % (op_def,))
        self.op_def = op_def

    def __call__(self, *args, **kwargs):
        if args:
            raise ValueError("Only keyword arguments are supported.")
        d = self.op_def
        spec = {"type": d.type, "inputs": {}, "outputs": {}, "attrs": {}}
        consumed = set()
        for slot in d.input_slots:
            if slot in kwargs:
                spec["inputs"][slot] = self._names(kwargs[slot])
                consumed.add(slot)
        for slot in d.output_slots:
            if slot in kwargs:
                spec["outputs"][slot] = self._names(kwargs[slot])
                consumed.add(slot)
        for key, value in kwargs.items():
            if key in consumed:
                continue
            # anything that is not an input/output slot is an attribute
            # (the OpDef does not enumerate attrs; kernels read them)
            spec["attrs"][key] = value
        return spec

    @staticmethod
    def _names(v):
        if isinstance(v, str):
            return [v]
        if isinstance(v, (list, tuple)):
            return list(v)
        return [v]


class OperatorFactory(object):
    """``Operator(type, **kwargs)`` entry point (reference op.py
    OperatorFactory)."""

    def __call__(self, op_type, *args, **kwargs):
        return OpDescCreationMethod(registry.get_op_def(op_type))(
            *args, **kwargs)

    def get_op_def(self, op_type):
        return registry.get_op_def(op_type)

    def types(self):
        return sorted(registry.OPS)


Operator = OperatorFactory()
