"""Profile-guided auto-configuration (ISSUE 9 tentpole).

PERF.md is a graveyard of hand-measured config decisions — b512 not
b1024, 4 bucket bounds not 6, Pallas flash attention only where the
measured A/B favors it, checkpoint cadence picked by eye — while the
compiler's own cost/memory accounting per program has been free at
runtime since the program-profile work (``monitor/program_profile.py``:
XLA ``cost_analysis``/``memory_analysis`` captured at the one compile
each signature already pays).  This module closes the loop: an
auto-tuner that searches the config space using that machinery instead
of blind timing sweeps.

Five knobs, five decision procedures (each a PURE function of
measurements, so the policy is unit-testable without a device):

* **batch size** (:func:`run_batch_ladder` / :func:`tune_batch_size`) —
  geometric probe upward.  Each rung pays exactly ONE compile (the
  ``Executor.cost_analysis`` explicit compile, which seeds the AOT
  dispatch slot, so the measured window that follows adds zero backend
  compiles), whose ``memory_analysis`` peak-HBM estimate rejects
  over-capacity rungs BEFORE any dispatch could OOM; once two rungs'
  peaks are known, the next rung's peak is PROJECTED (linear in batch)
  and an over-ceiling projection stops the ladder without even the
  probe compile.  Surviving rungs get a short measured
  step-time window; the ladder stops when seconds-per-example regresses
  (the PERF.md b512-not-b1024 shape: amortization plateaus, HBM-pressure
  scheduling takes over).
* **attention kernel per shape** (:func:`decide_attention_kernel` /
  :func:`tune_attention_kernel`) — XLA vs Pallas flash measured A/B at
  the model's (Tq, Tk, d, dtype), cached in a persistent
  :class:`AttentionDecisionTable` keyed by
  ``compile_cache.program_fingerprint`` + shape: a warm process reads
  the table and pays nothing.  Tuned choices are consulted by the
  ``fused_attention`` op itself (shape-matched), and a PINNED
  ``FLAGS_pallas_kernels`` — set by the user via env or ``set_flags``
  — always wins over the table.
* **bucket bounds** (:func:`choose_bucket_bounds`) — pick K bounds from
  an observed length histogram maximizing real-token fill, restricted
  to hardware-friendly multiples FIRST (the PERF.md r4 finding: six
  finer-but-ragged bounds measured WORSE than four MXU-friendly ones
  despite higher fill — raggedness loses more on the MXU than padding).
* **pipeline schedule + microbatch count** (:func:`decide_pipeline` /
  :func:`tune_pipeline`) — for programs whose ``pipeline_region`` ops
  run pipelined on a ``pp`` mesh: measure a short step window per
  (schedule, microbatches) candidate, reject candidates whose compiled
  peak-HBM estimate exceeds the ceiling (1F1B's M-independent
  activation memory is exactly what unlocks the larger-M rungs GPipe
  cannot afford), pick the fastest, and tie-break near-equal timings by
  the schedule table's exact bubble fraction then memory bound
  (``parallel.pipeline.schedule_stats``).  An explicit
  ``BuildStrategy.pipeline_schedule`` is a user pin the tuner records
  and respects.
* **checkpoint interval** (:func:`decide_checkpoint_interval`) —
  CheckFreq-style: the smallest interval whose measured on-step cost
  (snapshot, plus the full write in sync mode) stays under the overhead
  budget (default ``FLAGS_autotune_overhead_budget`` = 3.5%), bounded
  below by the async write's drain time so a save never backs up into
  the next snapshot; the guardian's measured rollback replay cost rides
  along as evidence (smaller intervals bound the replay — the formula
  already picks the smallest budget-feasible interval).

Decisions are recorded as a :class:`TunedConfig` artifact (JSON:
decision, evidence, probe measurements, run_id/fingerprints) consumed
by ``bench.py --autotune`` and ``contrib.Trainer(autotune=...)``, and
every decision publishes ``autotune/*`` monitor counters plus
``autotune_decision`` JSONL events so tuning is observable like
everything else.

**Rejection mechanism**: the batch ladder's ceiling is the preflight
HBM *estimate* (``FLAGS_autotune_hbm_bytes`` override, else
``FLAGS_preflight_hbm_bytes``, else the device's
``memory_stats()['bytes_limit']``) — candidates are rejected by the
compiler's own memory analysis before any dispatch, never by an OOM
crash.  That is what makes the probe testable on CPU with a fake limit.

**Pinning**: every tuned decision defers to an explicit user choice.
Flags set from the environment or via ``set_flags`` are *pinned*
(``flags.pinned()``); :meth:`TunedConfig.apply` skips pinned knobs and
records the skip in the decision trail.
"""

import contextlib
import json
import math
import os
import threading
import time

import numpy as np

__all__ = [
    "TunedConfig", "AttentionDecisionTable", "attention_table",
    "attention_choice", "attention_shape_key", "trace_token",
    "hbm_ceiling", "batch_ladder", "project_peak_hbm",
    "run_batch_ladder", "decide_attention_kernel", "token_fill",
    "choose_bucket_bounds", "decide_checkpoint_interval",
    "tune_batch_size", "tune_attention_kernel",
    "tune_checkpoint_interval", "measure_step_window",
    "decide_pipeline", "tune_pipeline",
    "quant_kernel_table", "quant_kernel_choice", "quant_shape_key",
    "decide_quant_kernel", "tune_quant_kernel",
    "decide_quantization", "tune_quantization",
]

_mu = threading.Lock()


def _flag(name, default):
    from . import flags

    try:
        return flags.flag(name)
    except KeyError:
        return default


def _event(record):
    from . import monitor

    ev = record.get("event")
    if ev == "autotune_decision":
        monitor.count("autotune/decisions")
    elif ev == "autotune_probe":
        monitor.count("autotune/probes")
    record.setdefault("ts", time.time())
    monitor.log_event(record)


# ---------------------------------------------------------------------------
# pure decision functions
# ---------------------------------------------------------------------------

def batch_ladder(start=32, max_batch=4096, factor=2):
    """Geometric candidate ladder: start, start*factor, ... <= max_batch."""
    start = max(1, int(start))
    out = []
    b = start
    while b <= max_batch:
        out.append(b)
        nxt = int(b * factor)
        b = nxt if nxt > b else b + 1
    return out


def project_peak_hbm(pairs, batch):
    """Project a candidate batch's estimated peak HBM from measured
    (batch, peak_bytes) pairs by least-squares linear fit — peak memory
    is affine in batch (activations/temps scale, params don't).  Needs
    >= 2 distinct batches; returns None otherwise."""
    pts = [(float(b), float(p)) for b, p in pairs if p]
    if len({b for b, _ in pts}) < 2:
        return None
    xs = np.array([b for b, _ in pts])
    ys = np.array([p for _, p in pts])
    slope, intercept = np.polyfit(xs, ys, 1)
    return float(intercept + slope * float(batch))


def run_batch_ladder(ladder, hbm_limit, probe_fn, measure_fn,
                     regress_tol=0.05, headroom=0.9):
    """The batch-size decision procedure, pure in its callbacks.

    ``probe_fn(batch) -> estimated peak HBM bytes (or None)`` — one
    compile's memory analysis; ``measure_fn(batch) -> measured seconds
    per step`` — a short dispatch window over the already-compiled
    executable.  ``hbm_limit`` of None/0 disables the memory gate.

    Walks ``ladder`` upward.  A rung whose PROJECTED peak (linear fit
    over the rungs already probed) exceeds ``headroom * hbm_limit``
    stops the ladder without its probe compile; a rung whose probed
    estimate exceeds the ceiling stops it before any dispatch; a rung
    whose measured seconds-per-example regresses more than
    ``regress_tol`` over the best-so-far stops it after its window.

    Returns the decision dict: ``chosen`` (best seconds-per-example
    among surviving rungs, None if none survived), per-candidate
    statuses and measurements, and the ceiling used.
    """
    limit = float(hbm_limit) if hbm_limit else None
    ceiling = limit * float(headroom) if limit else None
    candidates = []
    peaks = []                      # (batch, probed peak) pairs
    best = None                     # (s_per_example, batch, step_s)
    for b in ladder:
        cand = {"batch": int(b)}
        if ceiling is not None:
            projected = project_peak_hbm(peaks, b)
            if projected is not None and projected > ceiling:
                cand.update(status="rejected_projected_hbm",
                            projected_peak_hbm_bytes=int(projected))
                candidates.append(cand)
                break
            peak = probe_fn(b)
            if peak:
                cand["peak_hbm_bytes"] = int(peak)
                peaks.append((b, peak))
                if peak > ceiling:
                    cand["status"] = "rejected_hbm"
                    candidates.append(cand)
                    break
        else:
            peak = probe_fn(b)
            if peak:
                cand["peak_hbm_bytes"] = int(peak)
                peaks.append((b, peak))
        step_s = measure_fn(b)
        spe = step_s / float(b)
        cand.update(step_s=round(step_s, 6),
                    s_per_example=spe, status="ok")
        candidates.append(cand)
        if best is not None and spe > best[0] * (1.0 + regress_tol):
            cand["status"] = "regressed"
            break
        if best is None or spe < best[0]:
            best = (spe, int(b), step_s)
    decision = {
        "knob": "batch_size",
        "chosen": best[1] if best else None,
        "candidates": candidates,
        "hbm_limit_bytes": int(limit) if limit else None,
        "headroom": headroom,
        "regress_tol": regress_tol,
        "evidence": "hbm_preflight_estimate+measured_step_window",
    }
    if best:
        decision["chosen_s_per_example"] = best[0]
        decision["chosen_step_s"] = round(best[2], 6)
    return decision


def decide_attention_kernel(xla_step_s, pallas_step_s, min_speedup=1.03):
    """Pick the Pallas flash kernel only where the measured A/B favors
    it by at least ``min_speedup`` (the PERF.md shape: Pallas wins
    1.3-1.9x at T=4096 and LOSES ~1.5x at T<=64 — ties go to XLA, whose
    global fusion is the safer default)."""
    xla_step_s = float(xla_step_s)
    pallas_step_s = float(pallas_step_s)
    use_pallas = (pallas_step_s > 0
                  and xla_step_s / pallas_step_s >= float(min_speedup))
    return {"knob": "attention_kernel", "pallas": bool(use_pallas),
            "xla_step_s": round(xla_step_s, 6),
            "pallas_step_s": round(pallas_step_s, 6),
            "speedup": round(xla_step_s / pallas_step_s, 4)
            if pallas_step_s > 0 else None,
            "min_speedup": float(min_speedup),
            "evidence": "measured_ab_window"}


def _length_counts(lengths):
    """Normalize a length sample ({len: count} dict or iterable of
    ints) to a sorted (length, count) list."""
    if isinstance(lengths, dict):
        items = [(int(n), int(c)) for n, c in lengths.items() if c > 0]
    else:
        lengths = list(lengths)
        if lengths and isinstance(lengths[0], tuple):
            # already a (length, count) pairing (internal re-entry)
            items = [(int(n), int(c)) for n, c in lengths if c > 0]
        else:
            counts = {}
            for n in lengths:
                counts[int(n)] = counts.get(int(n), 0) + 1
            items = list(counts.items())
    if not items or min(n for n, _ in items) < 1:
        raise ValueError("lengths must be a non-empty sample of "
                         "positive ints")
    return sorted(items)


def token_fill(lengths, bounds):
    """Real-token fill fraction of a bound set over an observed length
    histogram: each sample pads to the smallest bound >= its length
    (samples above the top bound clamp to it — a real reader would
    truncate or reject).  fill = real tokens / padded tokens."""
    counts = _length_counts(lengths)
    bounds = sorted(int(b) for b in bounds)
    if not bounds:
        raise ValueError("bounds must be non-empty")
    real = padded = 0
    for n, c in counts:
        b = next((b for b in bounds if b >= n), bounds[-1])
        real += min(n, b) * c
        padded += b * c
    return real / float(padded)


def choose_bucket_bounds(lengths, k=4, multiple=16, max_len=None):
    """Pick up to ``k`` bucket bounds maximizing real-token fill over an
    observed length histogram, restricted to multiples of ``multiple``
    (hardware-friendly sizes FIRST, fill-optimal second — the PERF.md
    r4 ruling: bounds {16,32,48,64} beat six finer ragged bounds whose
    higher fill lost to poor MXU tiling).  The top bound always covers
    ``max_len`` (default: the sample's max).  Solved exactly by DP over
    the sorted candidates (optimal histogram partition) — polynomial in
    max_len/multiple, so long-context bound sets stay cheap."""
    counts = _length_counts(lengths)
    sample_max = counts[-1][0]
    max_len = int(max_len or sample_max)
    if max_len < sample_max:
        raise ValueError("max_len %d below the sample's max length %d"
                         % (max_len, sample_max))
    multiple = max(1, int(multiple))
    top = int(math.ceil(max_len / float(multiple))) * multiple
    cands = list(range(multiple, top + 1, multiple))
    k = max(1, min(int(k), len(cands)))
    # maximizing fill = minimizing padded tokens, which decomposes over
    # the chosen bounds: lengths in (prev_bound, bound] pad to bound.
    # DP over sorted candidates (optimal histogram partition, O(n^2 k))
    # — a long-context max_len yields a hundred-plus candidates, where
    # the naive subset enumeration explodes combinatorially.
    n = len(cands)
    pref = [0] * (n + 1)          # samples with length <= cands[i-1]
    it = iter(counts)
    cur = next(it, None)
    for i, c in enumerate(cands):
        pref[i + 1] = pref[i]
        while cur is not None and cur[0] <= c:
            pref[i + 1] += cur[1]
            cur = next(it, None)

    def seg(h, i):
        # padded tokens of lengths in (cands[h-1], cands[i-1]] at bound
        # cands[i-1]; h == 0 means "no smaller bound chosen"
        return (pref[i] - pref[h]) * cands[i - 1]

    INF = float("inf")
    dp = [[INF] * (k + 1) for _ in range(n + 1)]    # dp[i][j]: i-th
    parent = [[0] * (k + 1) for _ in range(n + 1)]  # cand is j-th bound
    for i in range(1, n + 1):
        dp[i][1] = seg(0, i)
        for j in range(2, min(k, i) + 1):
            for h in range(j - 1, i):
                cost = dp[h][j - 1] + seg(h, i)
                if cost < dp[i][j]:
                    dp[i][j] = cost
                    parent[i][j] = h
    best_j = min(range(1, k + 1), key=lambda j: dp[n][j])
    bounds = []
    i, j = n, best_j
    while j >= 1:
        bounds.append(cands[i - 1])
        i, j = parent[i][j], j - 1
    bounds.reverse()
    best_fill = token_fill(counts, bounds)
    return {"knob": "bucket_bounds",
            "chosen": bounds,
            "fill": round(best_fill, 4),
            "k": k, "multiple": multiple, "top_bound": top,
            "candidates_considered": len(cands),
            "pad_to_max_fill": round(token_fill(counts, [top]), 4),
            "evidence": "length_histogram_fill"}


def decide_checkpoint_interval(step_s, snapshot_s, save_s=0.0,
                               budget=None, async_save=True,
                               replay_step_s=None, min_interval=1,
                               max_interval=100000):
    """CheckFreq-style checkpoint cadence from measured costs.

    ``step_s``: measured steady-state step seconds; ``snapshot_s``: the
    synchronous device->host snapshot cost (the only on-step cost of an
    async save); ``save_s``: the full serialize+fsync+commit write span
    (on-step only in sync mode, but the async drain bound below needs
    it either way); ``budget``: max fraction of compute spent on
    checkpointing (default ``FLAGS_autotune_overhead_budget``).

    interval = the SMALLEST step count such that (a) on-step cost per
    interval stays under budget and (b) the async write drains inside
    the interval (a write slower than the interval's compute backs up
    into the next snapshot and the drain lands on the step path).
    Monotone non-decreasing in every measured cost.  ``replay_step_s``
    (default ``step_s``) prices the worst-case rollback replay of one
    interval — evidence for the guardian, not a constraint: the formula
    already picks the smallest budget-feasible interval, which is also
    the recovery-optimal one.
    """
    step_s = float(step_s)
    if step_s <= 0:
        raise ValueError("step_s must be positive")
    snapshot_s = max(0.0, float(snapshot_s))
    save_s = max(0.0, float(save_s))
    if budget is None:
        budget = float(_flag("autotune_overhead_budget", 0.035))
    budget = float(budget)
    if budget <= 0:
        raise ValueError("budget must be positive")
    on_step_cost = snapshot_s + (0.0 if async_save else save_s)
    interval = int(math.ceil(on_step_cost / (budget * step_s)))
    drain = int(math.ceil(save_s / step_s)) if async_save else 0
    interval = max(int(min_interval), interval, drain)
    interval = min(interval, int(max_interval))
    replay_step_s = float(replay_step_s if replay_step_s is not None
                          else step_s)
    return {"knob": "checkpoint_interval",
            "chosen": interval,
            "step_s": round(step_s, 6),
            "snapshot_s": round(snapshot_s, 6),
            "save_s": round(save_s, 6),
            "async_save": bool(async_save),
            "budget": budget,
            "overhead_frac": round(
                on_step_cost / (interval * step_s), 6),
            "drain_bound_steps": drain,
            "worst_case_replay_s": round(interval * replay_step_s, 6),
            "evidence": "measured_checkpoint_spans"}


# ---------------------------------------------------------------------------
# TunedConfig artifact
# ---------------------------------------------------------------------------

class TunedConfig:
    """The tuner's output artifact: a list of decisions with their
    evidence, serialized as JSON.  ``bench.py --autotune`` embeds it in
    the bench artifact; ``contrib.Trainer(autotune=...)`` consumes it;
    ``tools/autotune_report.py`` renders it for humans."""

    VERSION = 1

    def __init__(self, decisions=None, meta=None):
        self.decisions = list(decisions or [])
        self.meta = dict(meta or {})
        self.meta.setdefault("version", self.VERSION)
        if "run_id" not in self.meta:
            from . import monitor

            self.meta["run_id"] = monitor.run_id()
        self.meta.setdefault("created_ts", time.time())

    # -- content -------------------------------------------------------
    def add(self, decision, fingerprint=None, source="measured"):
        """Append one decision dict (the output of a decide_*/tune_*
        call), stamped with provenance."""
        d = dict(decision)
        if fingerprint:
            d["fingerprint"] = fingerprint
        d.setdefault("source", source)
        self.decisions.append(d)
        _event({"event": "autotune_decision", "knob": d.get("knob"),
                "chosen": d.get("chosen", d.get("pallas")),
                "source": d.get("source"),
                "fingerprint": d.get("fingerprint")})
        return d

    def get(self, knob):
        """The LAST decision for ``knob`` (latest wins), or None."""
        for d in reversed(self.decisions):
            if d.get("knob") == knob:
                return d
        return None

    def value(self, knob, default=None):
        d = self.get(knob)
        if d is None:
            return default
        return d.get("chosen", d.get("pallas", default))

    def as_dict(self):
        return {"meta": dict(self.meta),
                "decisions": [dict(d) for d in self.decisions]}

    # -- persistence ---------------------------------------------------
    def save(self, path):
        """Atomic JSON write; returns ``path``."""
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.as_dict(), f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path):
        with open(path) as f:
            doc = json.load(f)
        return cls(decisions=doc.get("decisions", []),
                   meta=doc.get("meta", {}))

    # -- application ---------------------------------------------------
    def apply(self):
        """Apply the flag-backed decisions to the process, RESPECTING
        pins: a flag the user set explicitly (env or ``set_flags``)
        always wins over the tuner.  Returns a list of (knob, outcome)
        pairs — outcome is "applied", "pinned" (user override wins), or
        "advisory" (knobs like batch size that callers read from the
        artifact rather than a flag).  Attention-kernel decisions
        install into the process :class:`AttentionDecisionTable` (the
        ``fused_attention`` op consults it per shape)."""
        from . import flags

        outcomes = []
        for d in self.decisions:
            knob = d.get("knob")
            if knob == "attention_kernel" and d.get("shape"):
                if flags.pinned("pallas_kernels"):
                    outcomes.append((knob, "pinned"))
                    continue
                attention_table().record(
                    d.get("fingerprint") or "", d["shape"],
                    bool(d.get("pallas")), d, persist=False)
                outcomes.append((knob, "applied"))
            elif knob == "quant_kernel" and d.get("shape"):
                if flags.pinned("pallas_kernels"):
                    outcomes.append((knob, "pinned"))
                    continue
                quant_kernel_table().record(
                    d.get("fingerprint") or "", d["shape"],
                    bool(d.get("pallas")), d, persist=False)
                outcomes.append((knob, "applied"))
            elif knob == "quantization":
                if flags.pinned("quantize_mode"):
                    outcomes.append((knob, "pinned"))
                    continue
                # consumed by the serving engines / quantize_inference
                # callers from the artifact, not a flag
                outcomes.append((knob, "advisory"))
            elif knob == "checkpoint_interval":
                # applied by the Trainer against its manager (not a
                # flag); recorded here so the trail is complete
                outcomes.append((knob, "advisory"))
            else:
                outcomes.append((knob, "advisory"))
        _event({"event": "autotune_applied",
                "outcomes": [list(o) for o in outcomes]})
        return outcomes


# ---------------------------------------------------------------------------
# persistent attention-kernel decision table
# ---------------------------------------------------------------------------

def attention_shape_key(q_shape, k_shape, dtype):
    """Stable shape key for the attention decision table: (Tq, Tk, d,
    dtype) — batch and head count don't change the kernel ruling's
    regime (the [T, T] score materialization does)."""
    return "T%d:K%d:d%d:%s" % (int(q_shape[2]), int(k_shape[2]),
                               int(q_shape[3]), np.dtype(dtype).name
                               if not isinstance(dtype, str) else dtype)


class AttentionDecisionTable:
    """Persistent per-shape XLA-vs-Pallas decisions, keyed by
    ``fingerprint + shape key``.  Lives as JSON under
    ``FLAGS_autotune_dir`` (in-memory only when the flag is unset), so a
    warm process — or a warm bench rung subprocess sharing the dir —
    reads the measured ruling and pays zero A/B compiles.

    Mutations bump a content token that ``compile_cache.
    trace_flag_values`` folds into every trace/AOT cache key: a changed
    ruling re-lowers instead of serving the other kernel's stale trace.
    """

    FILENAME = "attention_decisions.json"

    def __init__(self, dirname=None, filename=None):
        self._dir = dirname
        # the table machinery is knob-agnostic (string shape keys ->
        # pallas rulings); a second knob persists under its own file
        # (quant_kernel_table)
        self._filename = filename or self.FILENAME
        self._entries = {}
        self._loaded = False
        # content token cached as an immutable tuple: trace_token() is
        # on every executor cache-key computation (per step), so the
        # sorted rebuild happens per MUTATION, not per step
        self._token = None
        self._mu = threading.Lock()

    def _path(self):
        d = self._dir if self._dir is not None \
            else str(_flag("autotune_dir", "") or "")
        return os.path.join(d, self._filename) if d else None

    def _load_locked(self):
        if self._loaded:
            return
        self._loaded = True
        path = self._path()
        if not path or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                doc = json.load(f)
            entries = doc.get("entries", {})
            if isinstance(entries, dict):
                # on-disk rulings merge UNDER in-memory ones (the
                # running process's fresher measurements win)
                merged = dict(entries)
                merged.update(self._entries)
                self._entries = merged
                self._token = None
        except (ValueError, OSError):
            # a torn write must not poison tuning; re-measure instead
            self._entries = dict(self._entries)

    def _persist_locked(self):
        path = self._path()
        if not path:
            return
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"entries": self._entries}, f, indent=2,
                      sort_keys=True)
        os.replace(tmp, path)

    @staticmethod
    def _key(fingerprint, shape_key):
        return "%s|%s" % ((fingerprint or "")[:12], shape_key)

    def lookup(self, fingerprint, shape_key):
        """The ruling for (fingerprint, shape) — falling back to any
        fingerprint's ruling at the same shape (the regime is the
        shape's property; the fingerprint records provenance).  Returns
        the entry dict or None."""
        with self._mu:
            self._load_locked()
            e = self._entries.get(self._key(fingerprint, shape_key))
            if e is not None:
                return dict(e)
            suffix = "|" + shape_key
            newest = None
            for k, v in self._entries.items():
                if k.endswith(suffix) and (
                        newest is None
                        or v.get("ts", 0) >= newest.get("ts", 0)):
                    newest = v
            return dict(newest) if newest else None

    def record(self, fingerprint, shape_key, pallas, evidence=None,
               persist=True):
        entry = {"pallas": bool(pallas), "shape": shape_key,
                 "fingerprint": (fingerprint or "")[:12],
                 "ts": time.time()}
        if evidence:
            entry["evidence"] = {
                k: evidence[k] for k in ("xla_step_s", "pallas_step_s",
                                         "speedup", "min_speedup",
                                         "source")
                if k in evidence}
        with self._mu:
            self._load_locked()
            self._entries[self._key(fingerprint, shape_key)] = entry
            self._token = None
            if persist:
                self._persist_locked()
        return entry

    def entries(self):
        with self._mu:
            self._load_locked()
            return {k: dict(v) for k, v in self._entries.items()}

    def content_token(self):
        """Hashable digest of every ruling — part of the trace-cache
        key (two processes with identical tables key identically).
        Cached until the next mutation; the warm path is one attribute
        read."""
        t = self._token
        if t is not None:
            return t
        with self._mu:
            self._load_locked()
            if self._token is None:
                self._token = tuple(sorted(
                    (k, bool(v.get("pallas"))) for k, v in
                    self._entries.items()))
            return self._token

    def clear(self):
        with self._mu:
            self._entries.clear()
            self._loaded = True
            self._token = None


_table = [None]


def attention_table():
    """The process-global attention decision table."""
    with _mu:
        if _table[0] is None:
            _table[0] = AttentionDecisionTable()
        return _table[0]


def _active_table():
    """The table consulted on hot paths (the ``fused_attention`` op and
    the trace-cache token): the instantiated process table, or — when
    ``FLAGS_autotune_dir`` names a persisted table — a lazily loaded
    one (setting the dir IS the opt-in: a fresh process with the flag
    picks up the warm rulings without re-running the tuner).  None when
    neither exists.  Both callers share this helper so the trace key
    and the lowering always agree on which rulings are in force."""
    t = _table[0]
    if t is not None:
        return t
    if str(_flag("autotune_dir", "") or ""):
        return attention_table()
    return None


def reset_attention_table():
    """Drop the process table (tests); the on-disk file is untouched."""
    with _mu:
        _table[0] = None


_qtable = [None]
QUANT_FILENAME = "quant_kernel_decisions.json"


def quant_kernel_table():
    """The process-global dequant-matmul kernel decision table (same
    machinery as the attention table, its own persisted file)."""
    with _mu:
        if _qtable[0] is None:
            _qtable[0] = AttentionDecisionTable(filename=QUANT_FILENAME)
        return _qtable[0]


def _active_quant_table():
    t = _qtable[0]
    if t is not None:
        return t
    if str(_flag("autotune_dir", "") or ""):
        return quant_kernel_table()
    return None


def reset_quant_kernel_table():
    """Drop the process quant-kernel table (tests); disk untouched."""
    with _mu:
        _qtable[0] = None


def trace_token():
    """Token folded into every trace/AOT cache key
    (``compile_cache.trace_flag_values``): tuned kernel rulings are
    baked into the lowered jaxpr, so a changed table must re-lower
    rather than serve the other kernel's stale trace.  Covers BOTH
    per-shape tables (attention and dequant-matmul).  Cheap when no
    table exists (the overwhelmingly common case)."""
    parts = ()
    t = _active_table()
    if t is not None:
        parts += (("attention",) + t.content_token(),)
    q = _active_quant_table()
    if q is not None:
        parts += (("quant",) + q.content_token(),)
    return parts


def attention_choice(q_shape, k_shape, dtype):
    """The tuned kernel ruling for this attention shape, or None when
    there is none — or when the user PINNED ``FLAGS_pallas_kernels``
    (an explicit flag always beats the tuner).  Called by the
    ``fused_attention`` op at trace time."""
    t = _active_table()
    if t is None:
        return None
    from . import flags

    if flags.pinned("pallas_kernels"):
        return None
    e = t.lookup("", attention_shape_key(q_shape, k_shape, dtype))
    return None if e is None else bool(e["pallas"])


def quant_shape_key(m, k, n, dtype, mode="weight_only"):
    """Stable shape key for the dequant-matmul kernel table: the
    flattened GEMM dims plus activation dtype and quantization mode
    (the regime-setting properties)."""
    name = np.dtype(dtype).name if not isinstance(dtype, str) else dtype
    return "M%d:K%d:N%d:%s:%s" % (int(m), int(k), int(n), name, mode)


def quant_kernel_choice(m, k, n, dtype, mode="weight_only"):
    """The tuned Pallas-vs-XLA ruling for this dequant-matmul shape, or
    None when there is none — or when the user PINNED
    ``FLAGS_pallas_kernels``.  Called by the ``dequant_matmul`` op at
    trace time (the exact analog of :func:`attention_choice`)."""
    t = _active_quant_table()
    if t is None:
        return None
    from . import flags

    if flags.pinned("pallas_kernels"):
        return None
    e = t.lookup("", quant_shape_key(m, k, n, dtype, mode))
    return None if e is None else bool(e["pallas"])


# ---------------------------------------------------------------------------
# measurement drivers
# ---------------------------------------------------------------------------

def hbm_ceiling(device=None):
    """The tuner's device-memory ceiling in bytes:
    ``FLAGS_autotune_hbm_bytes`` when set (tests, CPU drills with a
    fake limit), else ``FLAGS_preflight_hbm_bytes``, else the device's
    own ``memory_stats()['bytes_limit']``; None = no gate (CPU backends
    usually report nothing)."""
    override = int(_flag("autotune_hbm_bytes", 0))
    if override > 0:
        return override
    from .monitor.program_profile import _device_capacity

    return _device_capacity(device)


def measure_step_window(exe, program, feed, fetch_list, steps=4,
                        warmup=1, scope=None):
    """Seconds per step over a short fetch-synced dispatch window.  The
    feed is staged on device once; the window dispatches through the
    executor's already-seeded AOT executable (``cost_analysis`` seeds
    it), so the window itself performs zero compiles."""
    import jax

    dev = exe.place.jax_device()
    staged = {k: jax.device_put(np.asarray(v), dev)
              for k, v in feed.items()}
    last = None
    for _ in range(max(0, warmup)):
        last = exe.run(program, feed=staged, fetch_list=fetch_list,
                       scope=scope, return_numpy=False)
    if last is not None:
        np.asarray(last[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        last = exe.run(program, feed=staged, fetch_list=fetch_list,
                       scope=scope, return_numpy=False)
    np.asarray(last[0])       # fetch-sync: true completion of the chain
    return (time.perf_counter() - t0) / float(steps)


@contextlib.contextmanager
def _probe_run(place):
    """A fresh scope + executor whose steps are tagged as PROBE work:
    the program-profile accounting marks probe-only signatures so the
    tuner's throwaway candidates never blend into the per-program
    report's wall-share/MFU rows (the A/B-rung pollution bug, fixed at
    the accounting layer)."""
    from . import scope as _scope
    from .executor import Executor
    from .monitor import program_profile

    s = _scope.Scope()
    with _scope.scope_guard(s), program_profile.probe_accounting():
        yield Executor(place), s


def tune_batch_size(main_program, startup_program, make_feed, fetch,
                    place, ladder=None, start=32, max_batch=4096,
                    probe_steps=4, warmup_steps=1, regress_tol=0.05,
                    headroom=0.9, config=None):
    """Tune the batch size for one program: run the geometric ladder
    with the HBM-preflight gate and short measured windows (see
    :func:`run_batch_ladder` for the policy).  ``make_feed(batch)``
    builds the feed dict at a candidate batch; the program itself is
    batch-agnostic (feed shapes pick the jit signature).

    Compiles exactly once per probed rung (the ``cost_analysis``
    explicit compile, which seeds the AOT dispatch slot the measured
    window reuses) — zero backend compiles beyond the declared ladder.
    Appends the decision to ``config`` when given; returns it."""
    from . import compile_cache
    from .executor import _coerce_feed
    from .framework import Variable
    from .monitor import program_profile

    fetch_list = [fetch]
    fetch_names = (fetch.name if isinstance(fetch, Variable)
                   else str(fetch),)
    fp = compile_cache.program_fingerprint(main_program)
    block = main_program.global_block()
    with _probe_run(place) as (exe, scope):
        exe.run(startup_program, scope=scope)
        dev = place.jax_device()
        limit = hbm_ceiling(dev)

        def probe_fn(b):
            feed = make_feed(b)
            exe.cost_analysis(main_program, feed, fetch_list,
                              scope=scope)
            # look the profile up by THIS rung's exact feed signature
            # (the executor's own coercion included): a warm registry
            # would otherwise serve the newest-captured profile — some
            # other batch's peak — and poison the ladder
            names = sorted(feed)
            sig = tuple(
                (n, tuple(v.shape), str(v.dtype)) for n, v in
                ((n, _coerce_feed(block, n, feed[n])) for n in names))
            prof = program_profile.get(fp, sig, kind="executor",
                                       fetch_names=fetch_names)
            peak = prof.peak_hbm_bytes if prof is not None else None
            _event({"event": "autotune_probe", "knob": "batch_size",
                    "batch": int(b), "peak_hbm_bytes": peak,
                    "fingerprint": fp[:12]})
            return peak

        def measure_fn(b):
            # probe_fn already ran for this rung (the ladder always
            # probes before it measures): the signature is compiled and
            # the AOT dispatch slot is seeded, so the window performs
            # zero additional compiles
            feed = make_feed(b)
            return measure_step_window(exe, main_program, feed,
                                       fetch_list, steps=probe_steps,
                                       warmup=warmup_steps, scope=scope)

        decision = run_batch_ladder(
            ladder or batch_ladder(start, max_batch), limit,
            probe_fn, measure_fn, regress_tol=regress_tol,
            headroom=headroom)
    if config is not None:
        config.add(decision, fingerprint=fp[:12])
    else:
        _event({"event": "autotune_decision", "knob": "batch_size",
                "chosen": decision["chosen"], "fingerprint": fp[:12]})
    return decision


def tune_attention_kernel(main_program, startup_program, feed, fetch,
                          place, shape, probe_steps=4, warmup_steps=1,
                          min_speedup=1.03, table=None, config=None):
    """Measured XLA-vs-Pallas A/B for one attention shape, served from
    the persistent decision table when warm (zero compiles).

    ``shape``: ``(q_shape, k_shape, dtype)`` of the model's attention —
    or a ready shape-key string.  The A/B flips
    ``FLAGS_pallas_kernels`` (and raises the flash kernel's seq gate to
    cover the shape) UNPINNED and restores both afterwards, so tuning
    never counts as the user's explicit choice."""
    from . import compile_cache, flags

    key = shape if isinstance(shape, str) else attention_shape_key(*shape)
    table = table or attention_table()
    fp = compile_cache.program_fingerprint(main_program)
    cached = table.lookup(fp, key)
    if cached is not None:
        decision = {"knob": "attention_kernel", "shape": key,
                    "pallas": bool(cached["pallas"]),
                    "evidence": "decision_table",
                    "cached": True}
        decision.update(cached.get("evidence") or {})
        if config is not None:
            config.add(decision, fingerprint=fp[:12], source="cached")
        return decision

    seq = 0
    if not isinstance(shape, str):
        seq = max(int(shape[0][2]), int(shape[1][2]))
    fetch_list = [fetch]
    measured = {}
    saved = flags.get_flags(["pallas_kernels",
                             "pallas_attention_max_seq"])
    saved_pins = {n: flags.pinned(n)
                  for n in ("pallas_kernels", "pallas_attention_max_seq")}
    try:
        for pallas in (False, True):
            updates = {"pallas_kernels": pallas}
            if pallas and seq > int(flags.flag(
                    "pallas_attention_max_seq")):
                updates["pallas_attention_max_seq"] = seq
            flags.set_flags(updates, pin=False)
            with _probe_run(place) as (exe, scope):
                exe.run(startup_program, scope=scope)
                exe.cost_analysis(main_program, feed, fetch_list,
                                  scope=scope)
                measured[pallas] = measure_step_window(
                    exe, main_program, feed, fetch_list,
                    steps=probe_steps, warmup=warmup_steps, scope=scope)
            _event({"event": "autotune_probe",
                    "knob": "attention_kernel", "shape": key,
                    "pallas": pallas,
                    "step_s": round(measured[pallas], 6)})
    finally:
        flags.set_flags({k: v for k, v in saved.items()}, pin=False)
        flags._restore_pins(saved_pins)
    decision = decide_attention_kernel(measured[False], measured[True],
                                       min_speedup=min_speedup)
    decision["shape"] = key
    table.record(fp, key, decision["pallas"], decision)
    if config is not None:
        config.add(decision, fingerprint=fp[:12])
    return decision


def _span_mean(name):
    """Mean of a ``span/<name>`` monitor histogram, or None."""
    from . import monitor

    h = monitor.registry().get("span/" + name)
    if h is None or not getattr(h, "count", 0):
        return None
    return h.sum / h.count


def tune_checkpoint_interval(step_s=None, snapshot_s=None, save_s=None,
                             budget=None, async_save=True,
                             replay_step_s=None, manager=None,
                             config=None):
    """Checkpoint cadence from MEASURED costs: explicit arguments win;
    otherwise the manager's own cost samples
    (``TrainStateCheckpointManager.measured_costs()``), then the
    monitor's ``span/checkpoint/{snapshot,save}`` histograms; ``step_s``
    falls back to the StepStats mean.  Raises when no step-time
    measurement exists (there is nothing profile-guided about a
    guess)."""
    costs = manager.measured_costs() if manager is not None else {}
    if snapshot_s is None:
        snapshot_s = costs.get("snapshot_s")
    if snapshot_s is None:
        snapshot_s = _span_mean("checkpoint/snapshot")
    if save_s is None:
        save_s = costs.get("save_s")
    if save_s is None:
        save_s = _span_mean("checkpoint/save")
    if snapshot_s is None and save_s is None:
        # zero-cost inputs would compute interval=1 (checkpoint every
        # step) from NO evidence — the opposite of the budget's intent
        raise ValueError(
            "tune_checkpoint_interval: no measured checkpoint cost "
            "(pass snapshot_s/save_s, or complete at least one save "
            "through the manager / a monitored run first)")
    if step_s is None:
        from . import monitor

        summ = monitor.step_stats().summary() or {}
        step_s = summ.get("mean_step_seconds")
    if not step_s:
        raise ValueError(
            "tune_checkpoint_interval: no measured step time (pass "
            "step_s, or run some monitored steps first)")
    decision = decide_checkpoint_interval(
        step_s, snapshot_s or 0.0, save_s or 0.0, budget=budget,
        async_save=async_save, replay_step_s=replay_step_s)
    if manager is not None and costs:
        decision["measured_saves"] = costs.get("n", 0)
    if config is not None:
        config.add(decision)
    else:
        _event({"event": "autotune_decision",
                "knob": "checkpoint_interval",
                "chosen": decision["chosen"]})
    return decision


# ---------------------------------------------------------------------------
# pipeline schedule + microbatch tuning
# ---------------------------------------------------------------------------

def decide_pipeline(candidates, tol=0.03):
    """Pure pipeline-schedule policy over measured candidates.

    ``candidates``: dicts with ``schedule``, ``microbatches``,
    ``step_s`` (None/absent = not measured), ``bubble_fraction``,
    ``in_flight``, and optionally ``rejected`` (HBM gate).  Picks the
    fastest measured candidate; everything within ``tol`` of it
    tie-breaks by (bubble fraction, in-flight memory bound, smaller M)
    — schedule accounting settles what timing noise cannot."""
    ok = [c for c in candidates
          if not c.get("rejected") and c.get("step_s")]
    if not ok:
        raise ValueError(
            "decide_pipeline: no measured candidate survived "
            "(all rejected by the HBM gate or unmeasured)")
    best = min(ok, key=lambda c: c["step_s"])
    near = [c for c in ok if c["step_s"] <= best["step_s"] * (1 + tol)]
    near.sort(key=lambda c: (c.get("bubble_fraction", 1.0),
                             c.get("in_flight", 1 << 30),
                             c["microbatches"]))
    chosen = near[0]
    return {"knob": "pipeline",
            "chosen": {"schedule": chosen["schedule"],
                       "microbatches": int(chosen["microbatches"])},
            "candidates": [dict(c) for c in candidates],
            "evidence": "measured_step_window"}


def tune_pipeline(main_program, startup_program, feed, fetch, mesh,
                  build_strategy=None, schedules=None,
                  microbatch_candidates=None, probe_steps=3,
                  warmup_steps=1, tol=0.03, headroom=0.9, config=None):
    """Choose the pipeline schedule and microbatch count for a program
    with ``pipeline_region`` ops on ``mesh``'s ``pp`` axis, the same
    way the batch ladder works: one compile per candidate, a short
    measured step window through the ParallelExecutor, the compiled
    peak-HBM estimate as a pre-dispatch rejection gate
    (:func:`hbm_ceiling` — CPU-testable with a fake limit), and the
    schedule table's exact bubble accounting as evidence and
    tie-breaker.  Decisions land in ``config`` (TunedConfig) with the
    full candidate table.

    Pin semantics: an explicit ``build_strategy.pipeline_schedule`` is
    the user's choice — recorded as a pinned decision, never measured
    over."""
    from . import compile_cache
    from . import scope as _scope
    from .framework import Variable
    from .monitor import program_profile
    from .parallel.mesh import AXIS_PP
    from .parallel.parallel_executor import ParallelExecutor
    from .parallel.pipeline import SCHEDULES, schedule_stats
    from .parallel.strategy import BuildStrategy

    bs = build_strategy or BuildStrategy()
    fp = compile_cache.program_fingerprint(main_program)
    pp = 1
    if AXIS_PP in mesh.axis_names:
        pp = mesh.devices.shape[mesh.axis_names.index(AXIS_PP)]
    region_stages = [int(op.attrs["stages"])
                     for op in main_program.global_block().ops
                     if op.type == "pipeline_region"]
    if pp <= 1 or not region_stages:
        raise ValueError(
            "tune_pipeline: program has no pipeline_region ops that "
            "would run pipelined on this mesh (pp=%d, regions=%d)"
            % (pp, len(region_stages)))

    if bs.pipeline_schedule is not None:
        decision = {"knob": "pipeline",
                    "chosen": {"schedule": bs.pipeline_schedule,
                               "microbatches":
                               bs.pipeline_microbatches},
                    "evidence": "pinned",
                    "candidates": []}
        if config is not None:
            config.add(decision, fingerprint=fp[:12], source="pinned")
        return decision

    batch = max((int(np.shape(v)[0]) for v in feed.values()
                 if np.ndim(v) >= 1), default=0)

    def _engages(sched):
        # mirrors the lowering's engagement test (pipeline_region's
        # pp_ok): a candidate that would silently run the SEQUENTIAL
        # fallback must never be measured as if it pipelined (its
        # bubble stats would be fabricated and could win the
        # tie-break).  Interleaved engages at any v >= 1 there.
        if sched == "interleaved":
            return all(sc % pp == 0 for sc in region_stages)
        return all(sc == pp for sc in region_stages)

    if schedules is None:
        schedules = [sc for sc in ("gpipe", "1f1b") if _engages(sc)]
        # the default list adds interleaved only when it brings v > 1
        # chunks per device — v == 1 is gpipe with a wrap edge, a
        # wasted compile to measure by default (an explicit
        # schedules=['interleaved'] still may)
        if all(sc % pp == 0 and sc // pp > 1 for sc in region_stages):
            schedules.append("interleaved")
        elif not schedules and _engages("interleaved"):
            # mixed region stage counts (some v == 1): interleaved is
            # the only schedule that pipelines them all — measure it
            # even though part of it degenerates to a wrapped gpipe
            schedules.append("interleaved")
        if not schedules:
            raise ValueError(
                "tune_pipeline: no schedule runs the program's "
                "pipeline regions (stages=%s) pipelined on this mesh "
                "(pp=%d)" % (region_stages, pp))
    for s in schedules:
        if s not in SCHEDULES:
            raise ValueError("unknown schedule %r" % s)
    if microbatch_candidates is None:
        microbatch_candidates = [m for m in (pp, 2 * pp, 4 * pp)
                                 if batch and batch % m == 0]
    if not microbatch_candidates:
        raise ValueError(
            "tune_pipeline: no microbatch candidate divides the batch "
            "(%d) — pass microbatch_candidates" % batch)

    limit = hbm_ceiling(mesh.devices.flat[0])
    fetch_list = [fetch]
    fetch_name = fetch.name if isinstance(fetch, Variable) else str(fetch)
    candidates = []
    with program_profile.probe_accounting():
        for sched in schedules:
            for m in microbatch_candidates:
                # every non-viable combination is RECORDED, never
                # silently skipped: the artifact's candidate table must
                # cover the searched space
                if sched == "interleaved" and m % pp:
                    candidates.append(
                        {"schedule": sched, "microbatches": int(m),
                         "rejected": "microbatches %% pp != 0 "
                                     "(interleaved groups of %d)" % pp})
                    continue
                if not _engages(sched):
                    candidates.append(
                        {"schedule": sched, "microbatches": int(m),
                         "rejected": "not pipelined on this mesh "
                                     "(stages=%s, pp=%d)"
                                     % (region_stages, pp)})
                    continue
                stats = [schedule_stats(
                    sched, pp, m, s // pp if sched == "interleaved"
                    else 1) for s in region_stages]
                cand = {"schedule": sched, "microbatches": int(m),
                        "bubble_fraction": round(
                            sum(st["idle_units"] for st in stats)
                            / max(1, sum(st["total_units"]
                                         for st in stats)), 4),
                        "in_flight": max(st["in_flight"]
                                         for st in stats)}
                cbs = BuildStrategy()
                for attr, val in vars(bs).items():
                    setattr(cbs, attr, val)
                cbs.pipeline_schedule = sched
                cbs.pipeline_microbatches = int(m)
                scope = _scope.Scope()
                try:
                    with _scope.scope_guard(scope):
                        from .executor import CPUPlace, Executor
                        Executor(CPUPlace()).run(startup_program,
                                                 scope=scope)
                        pe = ParallelExecutor(
                            loss_name=fetch_name, mesh=mesh,
                            build_strategy=cbs,
                            main_program=main_program, scope=scope)
                        # the profile registry keys by (fingerprint,
                        # feed sig, partition) — NOT by schedule — so a
                        # warm trace cache (a second tune call) serves
                        # a stale peak from some other candidate.  Only
                        # a capture that happened DURING this
                        # candidate's cold dispatch is evidence.
                        prof_before = program_profile.get(fp)
                        for _ in range(max(1, warmup_steps)):
                            pe.run(feed=feed, fetch_list=fetch_list)
                        prof = program_profile.get(fp)
                        peak = prof.peak_hbm_bytes \
                            if prof is not None \
                            and prof is not prof_before else None
                        cand["peak_hbm_bytes"] = peak
                        if limit and peak and peak > headroom * limit:
                            cand["rejected"] = "peak_hbm %d > %.0f" % (
                                peak, headroom * limit)
                        else:
                            t0 = time.perf_counter()
                            for _ in range(probe_steps):
                                out = pe.run(feed=feed,
                                             fetch_list=fetch_list)
                            np.asarray(out[0])
                            cand["step_s"] = round(
                                (time.perf_counter() - t0)
                                / probe_steps, 6)
                except Exception as e:  # noqa: BLE001 — a failed
                    # candidate is evidence, not a tuner crash
                    cand["rejected"] = "error: %s" % str(e)[:160]
                _event({"event": "autotune_probe", "knob": "pipeline",
                        "schedule": sched, "microbatches": int(m),
                        "step_s": cand.get("step_s"),
                        "rejected": cand.get("rejected"),
                        "fingerprint": fp[:12]})
                candidates.append(cand)
    decision = decide_pipeline(candidates, tol=tol)
    decision["mesh_pp"] = int(pp)
    if config is not None:
        config.add(decision, fingerprint=fp[:12])
    else:
        _event({"event": "autotune_decision", "knob": "pipeline",
                "chosen": decision["chosen"], "fingerprint": fp[:12]})
    return decision


# ---------------------------------------------------------------------------
# quantized execution: kernel A/B + accuracy-gated program A/B (ISSUE 14)
# ---------------------------------------------------------------------------

def decide_quant_kernel(xla_step_s, pallas_step_s, min_speedup=1.03):
    """Pick the Pallas fused dequant-matmul only where the measured A/B
    favors it by ``min_speedup`` (ties go to XLA, same policy as the
    attention kernel)."""
    xla_step_s = float(xla_step_s)
    pallas_step_s = float(pallas_step_s)
    use_pallas = (pallas_step_s > 0
                  and xla_step_s / pallas_step_s >= float(min_speedup))
    return {"knob": "quant_kernel", "pallas": bool(use_pallas),
            "xla_step_s": round(xla_step_s, 6),
            "pallas_step_s": round(pallas_step_s, 6),
            "speedup": round(xla_step_s / pallas_step_s, 4)
            if pallas_step_s > 0 else None,
            "min_speedup": float(min_speedup),
            "evidence": "measured_ab_window"}


def _quant_microbench(m, k, n, dtype, mode, seed=0):
    """A one-op dequant_matmul program + synthetic int8 weights for the
    kernel A/B (kernel speed only; accuracy is tune_quantization's
    job).  Returns (program, feed, state values, fetch var)."""
    from .framework import Operator, Program
    from .registry import infer_op

    prog = Program()
    block = prog.global_block()
    x = block.create_var(name="qmb_x", shape=(int(m), int(k)),
                         dtype=dtype, is_data=True)
    qw = block.create_var(name="qmb_w", shape=(int(k), int(n)),
                          dtype="int8", persistable=True)
    sc = block.create_var(name="qmb_s", shape=(int(n),),
                          dtype="float32", persistable=True)
    out = block.create_var(name="qmb_out", dtype=dtype)
    op = Operator(block, type="dequant_matmul",
                  inputs={"X": [x.name], "QWeight": [qw.name],
                          "Scale": [sc.name]},
                  outputs={"Out": [out.name]},
                  attrs={"x_num_col_dims": 1, "mode": mode})
    infer_op(op, block)
    block.ops.append(op)
    prog._version += 1
    rng = np.random.RandomState(seed)
    w = (rng.randn(k, n) * 0.05).astype(np.float32)
    s = (np.maximum(np.abs(w).max(axis=0), 1e-12) / 127.0).astype(
        np.float32)
    qwv = np.clip(np.round(w / s), -127, 127).astype(np.int8)
    feed = {"qmb_x": rng.randn(m, k).astype(np.float32)}
    return prog, feed, {"qmb_w": qwv, "qmb_s": s}, out


def tune_quant_kernel(m, k, n, dtype="float32", place=None,
                      mode="weight_only", probe_steps=4, warmup_steps=1,
                      min_speedup=1.03, table=None, config=None):
    """Measured Pallas-vs-XLA A/B for one dequant-matmul shape, served
    from the persistent quant-kernel decision table when warm (zero
    compiles) — the exact analog of :func:`tune_attention_kernel`.
    The A/B flips ``FLAGS_pallas_kernels`` UNPINNED and restores it, so
    tuning never counts as the user's explicit choice."""
    from . import compile_cache, flags
    from .executor import CPUPlace

    place = place if place is not None else CPUPlace()
    key = quant_shape_key(m, k, n, dtype, mode)
    table = table or quant_kernel_table()
    prog, feed, values, fetch = _quant_microbench(m, k, n, dtype, mode)
    fp = compile_cache.program_fingerprint(prog)
    cached = table.lookup(fp, key)
    if cached is not None:
        decision = {"knob": "quant_kernel", "shape": key,
                    "pallas": bool(cached["pallas"]),
                    "evidence": "decision_table", "cached": True}
        decision.update(cached.get("evidence") or {})
        if config is not None:
            config.add(decision, fingerprint=fp[:12], source="cached")
        return decision

    measured = {}
    saved = flags.get_flags(["pallas_kernels"])
    saved_pins = {"pallas_kernels": flags.pinned("pallas_kernels")}
    try:
        for pallas in (False, True):
            flags.set_flags({"pallas_kernels": pallas}, pin=False)
            with _probe_run(place) as (exe, scope):
                for name, v in values.items():
                    scope.set_var(name, v)
                exe.cost_analysis(prog, feed, [fetch], scope=scope)
                measured[pallas] = measure_step_window(
                    exe, prog, feed, [fetch], steps=probe_steps,
                    warmup=warmup_steps, scope=scope)
            _event({"event": "autotune_probe", "knob": "quant_kernel",
                    "shape": key, "pallas": pallas,
                    "step_s": round(measured[pallas], 6)})
    finally:
        flags.set_flags(saved, pin=False)
        flags._restore_pins(saved_pins)
    decision = decide_quant_kernel(measured[False], measured[True],
                                   min_speedup=min_speedup)
    decision["shape"] = key
    table.record(fp, key, decision["pallas"], decision)
    if config is not None:
        config.add(decision, fingerprint=fp[:12])
    return decision


def eval_delta(reference, outputs):
    """Relative-L1 accuracy delta between two fetch lists: the
    quantization gate's eval metric (0 = bit-identical; scale-free, so
    one budget covers logits and probabilities alike)."""
    num = den = 0.0
    for r, o in zip(reference, outputs):
        r = np.asarray(r, np.float64)
        o = np.asarray(o, np.float64)
        num += float(np.abs(o - r).sum())
        den += float(np.abs(r).sum())
    return num / (den + 1e-12)


def decide_quantization(fp_step_s, candidates, budget,
                        min_speedup=1.0, batch=None):
    """Pure quantization policy over measured candidates.

    ``candidates``: dicts with ``mode``, ``accuracy_delta``, ``step_s``
    (or ``rejected`` for a candidate that failed outright).  A candidate
    survives only when its accuracy delta is under ``budget`` AND it is
    at least ``min_speedup`` faster than full precision — otherwise
    full precision is kept (``chosen`` None).  Rejections stay in the
    candidate table as evidence."""
    fp_step_s = float(fp_step_s)
    ok = []
    cands = [dict(c) for c in candidates]
    for c in cands:
        if c.get("rejected"):
            continue
        delta = float(c.get("accuracy_delta", np.inf))
        step_s = float(c.get("step_s") or 0.0)
        speedup = fp_step_s / step_s if step_s > 0 else 0.0
        c["speedup_vs_fp"] = round(speedup, 4)
        if delta > float(budget):
            c["status"] = "rejected_accuracy"
            continue
        if speedup < float(min_speedup):
            c["status"] = "rejected_slower"
            continue
        c["status"] = "ok"
        ok.append(c)
    chosen = min(ok, key=lambda c: c["step_s"]) if ok else None
    decision = {"knob": "quantization",
                "chosen": chosen["mode"] if chosen else None,
                "fp_step_s": round(fp_step_s, 6),
                "accuracy_budget": float(budget),
                "min_speedup": float(min_speedup),
                "candidates": cands,
                "evidence": "measured_ab_window+eval_delta"}
    if batch:
        decision["fp_tok_s"] = round(batch / fp_step_s, 2)
    if chosen:
        decision["accuracy_delta"] = chosen["accuracy_delta"]
        decision["chosen_step_s"] = chosen["step_s"]
        if batch:
            decision["chosen_tok_s"] = round(batch / chosen["step_s"], 2)
    return decision


def tune_quantization(main_program, scope, feed, fetch_list, place,
                      modes=("weight_only", "dynamic"), budget=None,
                      probe_steps=4, warmup_steps=1, min_speedup=1.0,
                      candidates=None, config=None):
    """Accuracy-gated quantization A/B for one inference program: run
    the full-precision program as the reference, build (or accept) a
    quantized candidate per mode via the ``quantize_inference`` pass
    over the SAME scope, and keep the fastest candidate whose measured
    eval delta stays under ``budget``
    (``FLAGS_quantize_accuracy_budget``) — otherwise full precision is
    kept, with every rejection recorded as TunedConfig evidence.

    ``candidates`` optionally supplies prepared ``(mode, program)``
    pairs (the corruption drills inject broken scales this way);
    the default builds them with the pass.  A pinned
    ``FLAGS_quantize_mode`` is the operator's choice — recorded, never
    measured over."""
    from . import compile_cache, flags
    from .executor import Executor
    from .monitor import program_profile

    if budget is None:
        budget = float(_flag("quantize_accuracy_budget", 0.02))
    fp = compile_cache.program_fingerprint(main_program)
    if flags.pinned("quantize_mode"):
        mode = str(flags.flag("quantize_mode") or "off")
        decision = {"knob": "quantization",
                    "chosen": None if mode in ("", "off") else mode,
                    "accuracy_budget": float(budget),
                    "evidence": "pinned", "candidates": []}
        if config is not None:
            config.add(decision, fingerprint=fp[:12], source="pinned")
        return decision

    batch = max((int(np.shape(v)[0]) for v in feed.values()
                 if np.ndim(v) >= 1), default=0)
    with program_profile.probe_accounting():
        # shared scope, no donation: the quantized candidates read the
        # same master weights the reference program does
        exe = Executor(place, donate_state=False)
        ref = [np.asarray(r) for r in exe.run(
            main_program, feed=feed, fetch_list=fetch_list, scope=scope)]
        fp_step_s = measure_step_window(
            exe, main_program, feed, fetch_list, steps=probe_steps,
            warmup=warmup_steps, scope=scope)
        if candidates is None:
            from .transpiler.quantize_pass import quantize_inference

            candidates = [(mode, quantize_inference(
                main_program, scope=scope, mode=mode)) for mode in modes]
        cands = []
        for mode, qprog in candidates:
            cand = {"mode": mode}
            try:
                outs = exe.run(qprog, feed=feed, fetch_list=fetch_list,
                               scope=scope)
                cand["accuracy_delta"] = round(eval_delta(ref, outs), 6)
                step_s = measure_step_window(
                    exe, qprog, feed, fetch_list, steps=probe_steps,
                    warmup=warmup_steps, scope=scope)
                cand["step_s"] = round(step_s, 6)
                if batch:
                    cand["tok_s"] = round(batch / step_s, 2)
            except Exception as e:  # noqa: BLE001 — a failed candidate
                cand["rejected"] = "error: %s" % str(e)[:160]  # is
                # evidence, not a tuner crash
            _event({"event": "autotune_probe", "knob": "quantization",
                    "mode": mode,
                    "accuracy_delta": cand.get("accuracy_delta"),
                    "step_s": cand.get("step_s"),
                    "rejected": cand.get("rejected"),
                    "fingerprint": fp[:12]})
            cands.append(cand)
    decision = decide_quantization(fp_step_s, cands, budget,
                                   min_speedup=min_speedup, batch=batch)
    if config is not None:
        config.add(decision, fingerprint=fp[:12])
    else:
        _event({"event": "autotune_decision", "knob": "quantization",
                "chosen": decision["chosen"], "fingerprint": fp[:12]})
    return decision


# ---------------------------------------------------------------------------
# serving decode tuners (ISSUE 16): int8 KV gate + speculation k
# ---------------------------------------------------------------------------

def tune_kv_quantization(build_spec, prompts, place=None,
                         max_new_tokens=8, budget=None, min_speedup=0.0,
                         config=None):
    """Accuracy gate for int8 KV pages, riding ``tune_quantization``'s
    discipline: drive the SAME weights (same build seed/prefix, fresh
    scope each) through a f32-KV paged engine as the reference and an
    int8-KV paged engine as the candidate, compare the per-step greedy
    logits with :func:`eval_delta`, and keep int8 KV only when the
    delta stays under ``budget`` (``FLAGS_quantize_accuracy_budget``).
    A rejection is recorded as TunedConfig evidence, exactly like a
    rejected weight-quantization candidate.

    ``build_spec(kv_dtype)`` -> a paged DecoderSpec (``kv_dtype`` is
    ``None`` for the f32 reference, ``"int8"`` for the candidate).
    ``min_speedup`` defaults to 0: int8 KV is an HBM-capacity knob
    (half the pool bytes), not a latency knob — it must not LOSE
    accuracy, but it does not have to win time."""
    import time as _time

    from .executor import CPUPlace
    from .serving.engine import GenerationEngine

    if budget is None:
        budget = float(_flag("quantize_accuracy_budget", 0.02))
    place = place or CPUPlace()

    def _drive(kv_dtype):
        spec = build_spec(kv_dtype)
        eng = GenerationEngine(spec, place=place,
                               max_new_tokens=max_new_tokens,
                               timeout_s=600.0, record_logits=True)
        try:
            t0 = _time.monotonic()
            outs = [eng.submit(p).result(1200) for p in prompts]
            wall = _time.monotonic() - t0
        finally:
            eng.close()
        toks = sum(len(o["tokens"]) for o in outs)
        logits = [row for o in outs for row in o["logits"]]
        tokens = [tuple(o["tokens"]) for o in outs]
        return logits, tokens, wall / max(toks, 1)

    ref_logits, ref_tokens, fp_step_s = _drive(None)
    cand = {"mode": "kv_int8"}
    try:
        q_logits, q_tokens, q_step_s = _drive("int8")
        cand["accuracy_delta"] = round(eval_delta(ref_logits, q_logits),
                                       6)
        cand["step_s"] = round(q_step_s, 6)
        cand["greedy_tokens_match"] = q_tokens == ref_tokens
    except Exception as e:  # noqa: BLE001 — evidence, not a crash
        cand["rejected"] = "error: %s" % str(e)[:160]
    _event({"event": "autotune_probe", "knob": "kv_quantization",
            "mode": "kv_int8",
            "accuracy_delta": cand.get("accuracy_delta"),
            "step_s": cand.get("step_s"),
            "rejected": cand.get("rejected")})
    decision = decide_quantization(fp_step_s, [cand], budget,
                                   min_speedup=min_speedup)
    decision["knob"] = "kv_quantization"
    decision["evidence"] = "paged_generation_ab+eval_delta"
    if config is not None:
        config.add(decision)
    else:
        _event({"event": "autotune_decision", "knob": "kv_quantization",
                "chosen": decision["chosen"]})
    return decision


def tune_speculation_k(make_engine, prompts, candidates=(None, 2, 4),
                       config=None):
    """Learn the speculative-decoding ``k`` for a workload: drive the
    same prompt set through ``make_engine(k)`` for each candidate
    (``None`` = speculation off, the baseline) and keep the fastest in
    decode tokens/second.  Greedy invariance is part of the gate: a
    candidate whose outputs differ from the baseline is rejected
    regardless of speed (speculative decoding must be a pure latency
    transform).  The workload decides — a weak draft (low acceptance)
    makes every k>1 SLOWER than the baseline and the tuner keeps
    ``None``."""
    import time as _time

    baseline_tokens = None
    cands = []
    for k in candidates:
        cand = {"k": k}
        try:
            eng = make_engine(k)
            try:
                t0 = _time.monotonic()
                outs = [eng.submit(p).result(1200) for p in prompts]
                wall = _time.monotonic() - t0
                toks = sum(len(o["tokens"]) for o in outs)
                tokens = [tuple(o["tokens"]) for o in outs]
                snap = eng.metrics.paged_snapshot()
            finally:
                eng.close()
            cand["tok_s"] = round(toks / max(wall, 1e-9), 2)
            cand["acceptance_rate"] = snap.get("spec_acceptance_rate")
            if k is None:
                baseline_tokens = tokens
            elif baseline_tokens is not None \
                    and tokens != baseline_tokens:
                cand["rejected"] = "greedy_outputs_diverged"
        except Exception as e:  # noqa: BLE001
            cand["rejected"] = "error: %s" % str(e)[:160]
        _event({"event": "autotune_probe", "knob": "speculation_k",
                "k": k, "tok_s": cand.get("tok_s"),
                "acceptance_rate": cand.get("acceptance_rate"),
                "rejected": cand.get("rejected")})
        cands.append(cand)
    ok = [c for c in cands if not c.get("rejected")
          and c.get("tok_s")]
    best = max(ok, key=lambda c: c["tok_s"]) if ok else None
    decision = {"knob": "speculation_k",
                "chosen": best["k"] if best else None,
                "candidates": cands,
                "evidence": "measured_generation_window"}
    if best:
        decision["chosen_tok_s"] = best["tok_s"]
    if config is not None:
        config.add(decision)
    else:
        _event({"event": "autotune_decision", "knob": "speculation_k",
                "chosen": decision["chosen"]})
    return decision
