"""Scope: the runtime store of variable values.

Capability parity with the reference's hierarchical Scope
(``paddle/fluid/framework/scope.h:41``: name->Variable map with parent
lookup and kid scopes) — TPU-native: values are jax Arrays (committed to
devices by the executor), the map is a plain dict, and kid scopes are used
for executor-local temporaries.
"""

import contextlib

__all__ = ["Scope", "global_scope", "scope_guard"]


class Scope:
    def __init__(self, parent=None):
        self._vars = {}
        self.parent = parent
        self.kids = []

    def new_scope(self):
        kid = Scope(parent=self)
        self.kids.append(kid)
        return kid

    def drop_kids(self):
        self.kids = []

    def set_var(self, name, value):
        self._vars[name] = value

    def has_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return True
            s = s.parent
        return False

    def find_var(self, name):
        """Find in this scope or ancestors (scope.h FindVar)."""
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def var(self, name):
        v = self.find_var(name)
        if v is None:
            raise KeyError("variable %r not found in scope" % name)
        return v

    def erase(self, name):
        self._vars.pop(name, None)

    def local_var_names(self):
        return list(self._vars.keys())

    def items(self):
        return self._vars.items()

    def __contains__(self, name):
        return self.has_var(name)


_global_scope = Scope()


def global_scope():
    return _global_scope


def _switch_scope(scope):
    """Swap the global scope, returning the previous one (reference
    executor.py:41 ``_switch_scope`` — the primitive under
    ``scope_guard``)."""
    global _global_scope
    prev = _global_scope
    _global_scope = scope
    return prev


@contextlib.contextmanager
def scope_guard(scope):
    """Temporarily swap the global scope (reference executor.py:47)."""
    prev = _switch_scope(scope)
    try:
        yield
    finally:
        _switch_scope(prev)
