/* Native training demo — reference
 * paddle/fluid/train/demo/demo_trainer.cc:1 re-hosted on the TPU
 * stack's C ABI: a pure C++ process loads a SAVED training program
 * (forward + backward + optimizer ops serialized by
 * io.save_train_program — no Python graph build), steps it on
 * synthesized batches, prints the loss per step exactly as the
 * reference demo does, and saves the trained parameters.
 *
 * Usage: demo_trainer <train_program_dir> [steps] [save_dir] [python_exe]
 */
#include <cstdio>
#include <cstdlib>

#include "paddle_capi.h"

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr,
            "usage: %s <train_program_dir> [steps] [save_dir] [python]\n",
            argv[0]);
    return 2;
  }
  int steps = argc > 2 ? atoi(argv[2]) : 10;
  const char* save_dir = argc > 3 ? argv[3] : nullptr;

  if (pd_init(argc > 4 ? argv[4] : nullptr) != 0) {
    fprintf(stderr, "init failed: %s\n", pd_last_error());
    return 1;
  }
  pd_trainer* t = pd_trainer_create(argv[1], nullptr, "cpu");
  if (t == nullptr) {
    fprintf(stderr, "create failed: %s\n", pd_last_error());
    return 1;
  }
  double first = 0.0;
  double loss = 0.0;
  for (int i = 0; i < steps; ++i) {
    if (pd_trainer_step_synth(t, 16, &loss) != 0) {
      fprintf(stderr, "step failed: %s\n", pd_last_error());
      return 1;
    }
    if (i == 0) first = loss;
    printf("step: %d loss: %f\n", i, loss);
  }
  if (save_dir != nullptr && pd_trainer_save(t, save_dir) != 0) {
    fprintf(stderr, "save failed: %s\n", pd_last_error());
    return 1;
  }
  pd_trainer_destroy(t);
  printf("first_loss: %f last_loss: %f\n", first, loss);
  printf("OK\n");
  return 0;
}
