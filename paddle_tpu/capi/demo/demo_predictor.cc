/* Native inference demo — reference paddle/capi deployment flow
 * (capi/examples) re-hosted on the TPU stack's C ABI.
 *
 * Usage: demo_predictor <model_dir> [python_exe]
 *
 * Reads the model's feed metadata through pd_predictor_io_json, feeds a
 * deterministic ramp into every float input (batch of 4), runs, and
 * prints each output's name/shape and first values — a pure C++
 * process exercising create -> introspect -> run -> release.
 */
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "paddle_capi.h"

/* minimal parse of the io JSON: find "feeds" entries' shape arrays.
 * (The demo avoids a JSON dependency; shapes are read with sscanf over
 * the known emitter format.) */
struct FeedInfo {
  std::string name;
  std::vector<int64_t> shape;
  std::string dtype;
};

static std::vector<FeedInfo> parse_feeds(const std::string& js) {
  std::vector<FeedInfo> feeds;
  size_t pos = 0;
  while ((pos = js.find("{\"name\": \"", pos)) != std::string::npos) {
    FeedInfo f;
    pos += 10;
    size_t e = js.find('"', pos);
    f.name = js.substr(pos, e - pos);
    size_t sh = js.find("\"shape\": [", pos);
    if (sh == std::string::npos) break;
    sh += 10;
    size_t sh_end = js.find(']', sh);
    std::string nums = js.substr(sh, sh_end - sh);
    const char* c = nums.c_str();
    while (*c != '\0') {
      long long v = strtoll(c, const_cast<char**>(&c), 10);
      f.shape.push_back(v);
      while (*c == ',' || *c == ' ') ++c;
    }
    size_t dt = js.find("\"dtype\": \"", pos);
    if (dt != std::string::npos) {
      dt += 10;
      f.dtype = js.substr(dt, js.find('"', dt) - dt);
    }
    feeds.push_back(f);
    pos = sh_end;
  }
  return feeds;
}

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <model_dir> [python_exe]\n", argv[0]);
    return 2;
  }
  if (pd_init(argc > 2 ? argv[2] : nullptr) != 0) {
    fprintf(stderr, "init failed: %s\n", pd_last_error());
    return 1;
  }
  pd_predictor* p = pd_predictor_create(argv[1], "cpu");
  if (p == nullptr) {
    fprintf(stderr, "create failed: %s\n", pd_last_error());
    return 1;
  }
  char* js = pd_predictor_io_json(p);
  if (js == nullptr) {
    fprintf(stderr, "io_json failed: %s\n", pd_last_error());
    return 1;
  }
  std::vector<FeedInfo> feeds = parse_feeds(js);
  pd_free(js);

  const int64_t batch = 4;
  std::vector<pd_tensor> ins;
  std::vector<std::vector<float>> buffers;
  std::vector<std::vector<int64_t>> shapes;
  buffers.reserve(feeds.size());
  shapes.reserve(feeds.size());
  for (const FeedInfo& f : feeds) {
    if (f.dtype != "float32") {
      fprintf(stderr, "demo feeds float32 models only (got %s for %s)\n",
              f.dtype.c_str(), f.name.c_str());
      return 1;
    }
    std::vector<int64_t> shape = f.shape;
    int64_t numel = 1;
    for (size_t d = 0; d < shape.size(); ++d) {
      if (shape[d] < 0) shape[d] = batch;
      numel *= shape[d];
    }
    buffers.emplace_back(static_cast<size_t>(numel));
    std::vector<float>& buf = buffers.back();
    for (int64_t i = 0; i < numel; ++i) {
      buf[static_cast<size_t>(i)] =
          static_cast<float>(i % 17) / 17.0f - 0.5f;
    }
    shapes.push_back(shape);
    pd_tensor t;
    memset(&t, 0, sizeof(t));
    t.name = const_cast<char*>(f.name.c_str());
    t.dtype = PD_FLOAT32;
    t.shape = shapes.back().data();
    t.rank = static_cast<int32_t>(shapes.back().size());
    t.data = buf.data();
    t.data_size = numel * static_cast<int64_t>(sizeof(float));
    ins.push_back(t);
  }

  pd_tensor* outs = nullptr;
  int32_t n_out = 0;
  if (pd_predictor_run(p, ins.data(), static_cast<int32_t>(ins.size()),
                       &outs, &n_out) != 0) {
    fprintf(stderr, "run failed: %s\n", pd_last_error());
    return 1;
  }
  for (int32_t i = 0; i < n_out; ++i) {
    printf("output %s shape=[", outs[i].name);
    int64_t numel = 1;
    for (int32_t d = 0; d < outs[i].rank; ++d) {
      printf("%s%lld", d ? "," : "",
             static_cast<long long>(outs[i].shape[d]));
      numel *= outs[i].shape[d];
    }
    printf("] first=");
    const float* vals = static_cast<const float*>(outs[i].data);
    for (int64_t j = 0; j < (numel < 5 ? numel : 5); ++j) {
      printf("%s%.4f", j ? "," : "", vals[j]);
    }
    printf("\n");
    pd_tensor_release(&outs[i]);
  }
  pd_free(outs);
  pd_predictor_destroy(p);
  printf("OK\n");
  return 0;
}
