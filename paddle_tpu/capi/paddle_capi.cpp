/* C ABI implementation: embeds CPython and drives paddle_tpu.capi._host.
 *
 * Design (see paddle_capi.h): the only Python surface touched is the
 * flat functions of _host.py with (name, dtype, shape, bytes) tensor
 * quads, so this file is pure CPython-API marshalling — no numpy
 * headers, no pybind11 (not available in this image; the CPython API
 * is the binding layer, like recordio uses a C ABI + ctypes).
 *
 * GIL protocol: pd_init releases the GIL after bootstrapping; every ABI
 * call brackets itself with PyGILState_Ensure/Release, which also makes
 * the library safe to load into an already-running Python process
 * (tests drive it via ctypes that way).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdlib>
#include <cstring>
#include <string>

#include "paddle_capi.h"

namespace {

thread_local std::string g_last_error;
PyObject* g_host = nullptr;        /* paddle_tpu.capi._host */
PyThreadState* g_main_ts = nullptr;
bool g_we_initialized = false;

void set_error_from_python() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

struct Gil {
  PyGILState_STATE st;
  Gil() : st(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(st); }
};

PyObject* host() {
  if (g_host == nullptr) {
    g_host = PyImport_ImportModule("paddle_tpu.capi._host");
    if (g_host == nullptr) set_error_from_python();
  }
  return g_host;
}

const char* dtype_name(pd_dtype d) {
  switch (d) {
    case PD_FLOAT32: return "float32";
    case PD_FLOAT64: return "float64";
    case PD_INT32: return "int32";
    case PD_INT64: return "int64";
  }
  return "float32";
}

int dtype_enum(const std::string& s, pd_dtype* out) {
  if (s == "float32") { *out = PD_FLOAT32; return 0; }
  if (s == "float64") { *out = PD_FLOAT64; return 0; }
  if (s == "int32") { *out = PD_INT32; return 0; }
  if (s == "int64") { *out = PD_INT64; return 0; }
  return -1;
}

/* pd_tensor[] -> list[(name, dtype, shape, bytes)] */
PyObject* tensors_to_py(const pd_tensor* ins, int32_t n) {
  PyObject* list = PyList_New(n);
  if (list == nullptr) return nullptr;
  for (int32_t i = 0; i < n; ++i) {
    const pd_tensor& t = ins[i];
    PyObject* shape = PyTuple_New(t.rank);
    for (int32_t d = 0; d < t.rank; ++d) {
      PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(t.shape[d]));
    }
    PyObject* quad = Py_BuildValue(
        "(s s N y#)", t.name, dtype_name(t.dtype), shape,
        static_cast<const char*>(t.data),
        static_cast<Py_ssize_t>(t.data_size));
    if (quad == nullptr) {
      Py_DECREF(list);
      return nullptr;
    }
    PyList_SET_ITEM(list, i, quad);
  }
  return list;
}

/* list[(name, dtype, shape, bytes)] -> malloc'd pd_tensor[] */
int tensors_from_py(PyObject* list, pd_tensor** outs, int32_t* n_out) {
  if (!PyList_Check(list)) {
    g_last_error = "host returned non-list";
    return -1;
  }
  Py_ssize_t n = PyList_GET_SIZE(list);
  pd_tensor* arr =
      static_cast<pd_tensor*>(calloc(static_cast<size_t>(n > 0 ? n : 1),
                                     sizeof(pd_tensor)));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* quad = PyList_GET_ITEM(list, i);
    const char* name = nullptr;
    const char* dtype = nullptr;
    PyObject* shape = nullptr;
    const char* data = nullptr;
    Py_ssize_t data_len = 0;
    if (!PyArg_ParseTuple(quad, "ssOy#", &name, &dtype, &shape, &data,
                          &data_len)) {
      set_error_from_python();
      for (Py_ssize_t j = 0; j < i; ++j) pd_tensor_release(&arr[j]);
      free(arr);
      return -1;
    }
    pd_tensor& t = arr[i];
    t.name = strdup(name);
    if (dtype_enum(dtype, &t.dtype) != 0) {
      g_last_error = std::string("unsupported output dtype ") + dtype;
      for (Py_ssize_t j = 0; j <= i; ++j) pd_tensor_release(&arr[j]);
      free(arr);
      return -1;
    }
    t.rank = static_cast<int32_t>(PyTuple_GET_SIZE(shape));
    t.shape = static_cast<int64_t*>(
        malloc(sizeof(int64_t) * static_cast<size_t>(t.rank)));
    for (int32_t d = 0; d < t.rank; ++d) {
      t.shape[d] = PyLong_AsLongLong(PyTuple_GET_ITEM(shape, d));
    }
    t.data_size = static_cast<int64_t>(data_len);
    t.data = malloc(static_cast<size_t>(data_len));
    memcpy(t.data, data, static_cast<size_t>(data_len));
  }
  *outs = arr;
  *n_out = static_cast<int32_t>(n);
  return 0;
}

/* call host fn; returns new ref or nullptr with error set */
PyObject* call_host(const char* fn, PyObject* args) {
  PyObject* mod = host();
  if (mod == nullptr) {
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* f = PyObject_GetAttrString(mod, fn);
  if (f == nullptr) {
    set_error_from_python();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (r == nullptr) set_error_from_python();
  return r;
}

int64_t handle_of(void* p) {
  return static_cast<int64_t>(reinterpret_cast<intptr_t>(p));
}

}  // namespace

extern "C" {

int pd_init(const char* python_exe) {
  if (Py_IsInitialized()) return 0; /* loaded into a live process */
  if (g_we_initialized) return 0;

  const char* exe = python_exe;
  if (exe == nullptr || exe[0] == '\0') exe = getenv("PD_PYTHON");
  if (exe == nullptr || exe[0] == '\0') exe = "python3";

  PyConfig config;
  PyConfig_InitPythonConfig(&config);
  /* pointing program_name at the venv python makes site resolve the
   * venv via pyvenv.cfg, exactly like launching that interpreter */
  PyStatus st = PyConfig_SetBytesString(&config, &config.program_name, exe);
  if (PyStatus_Exception(st)) {
    g_last_error = "PyConfig program_name failed";
    PyConfig_Clear(&config);
    return -1;
  }
  st = Py_InitializeFromConfig(&config);
  PyConfig_Clear(&config);
  if (PyStatus_Exception(st)) {
    g_last_error = "Py_InitializeFromConfig failed";
    return -1;
  }
  g_we_initialized = true;
  /* release the GIL so every ABI call can take it uniformly */
  g_main_ts = PyEval_SaveThread();
  return 0;
}

const char* pd_last_error(void) { return g_last_error.c_str(); }

/* ---- predictor ---- */

pd_predictor* pd_predictor_create(const char* model_dir,
                                  const char* device) {
  Gil gil;
  PyObject* r = call_host(
      "predictor_create",
      Py_BuildValue("(ss)", model_dir, device ? device : "cpu"));
  if (r == nullptr) return nullptr;
  long long h = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return reinterpret_cast<pd_predictor*>(static_cast<intptr_t>(h));
}

char* pd_predictor_io_json(pd_predictor* p) {
  Gil gil;
  PyObject* r = call_host("predictor_io_json",
                          Py_BuildValue("(L)", handle_of(p)));
  if (r == nullptr) return nullptr;
  const char* s = PyUnicode_AsUTF8(r);
  char* out = s ? strdup(s) : nullptr;
  Py_DECREF(r);
  return out;
}

int pd_predictor_run(pd_predictor* p, const pd_tensor* ins, int32_t n_in,
                     pd_tensor** outs, int32_t* n_out) {
  Gil gil;
  PyObject* feeds = tensors_to_py(ins, n_in);
  if (feeds == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* r = call_host("predictor_run",
                          Py_BuildValue("(LN)", handle_of(p), feeds));
  if (r == nullptr) return -1;
  int rc = tensors_from_py(r, outs, n_out);
  Py_DECREF(r);
  return rc;
}

void pd_predictor_destroy(pd_predictor* p) {
  if (p == nullptr || !Py_IsInitialized()) return;
  Gil gil;
  PyObject* r =
      call_host("predictor_destroy", Py_BuildValue("(L)", handle_of(p)));
  Py_XDECREF(r);
}

/* ---- trainer ---- */

pd_trainer* pd_trainer_create(const char* model_dir,
                              const char* params_dir,
                              const char* device) {
  Gil gil;
  PyObject* r = call_host(
      "trainer_create",
      Py_BuildValue("(sss)", model_dir, params_dir ? params_dir : "",
                    device ? device : "cpu"));
  if (r == nullptr) return nullptr;
  long long h = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return reinterpret_cast<pd_trainer*>(static_cast<intptr_t>(h));
}

int pd_trainer_step(pd_trainer* t, const pd_tensor* ins, int32_t n_in,
                    double* loss) {
  Gil gil;
  PyObject* feeds = tensors_to_py(ins, n_in);
  if (feeds == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* r = call_host("trainer_step",
                          Py_BuildValue("(LN)", handle_of(t), feeds));
  if (r == nullptr) return -1;
  *loss = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return 0;
}

int pd_trainer_step_synth(pd_trainer* t, int32_t batch_size,
                          double* loss) {
  Gil gil;
  PyObject* r = call_host(
      "trainer_step_synth",
      Py_BuildValue("(Li)", handle_of(t), static_cast<int>(batch_size)));
  if (r == nullptr) return -1;
  *loss = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return 0;
}

int pd_trainer_save(pd_trainer* t, const char* dirname) {
  Gil gil;
  PyObject* r = call_host("trainer_save",
                          Py_BuildValue("(Ls)", handle_of(t), dirname));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

void pd_trainer_destroy(pd_trainer* t) {
  if (t == nullptr || !Py_IsInitialized()) return;
  Gil gil;
  PyObject* r =
      call_host("trainer_destroy", Py_BuildValue("(L)", handle_of(t)));
  Py_XDECREF(r);
}

/* ---- memory ---- */

void pd_tensor_release(pd_tensor* t) {
  if (t == nullptr) return;
  free(t->name);
  free(t->shape);
  free(t->data);
  t->name = nullptr;
  t->shape = nullptr;
  t->data = nullptr;
}

void pd_free(void* p) { free(p); }

}  /* extern "C" */
