"""Native C ABI for deployment and train-from-saved-program.

Parity: reference ``paddle/capi/`` (C inference ABI, ``capi.h``) and
``paddle/fluid/train/demo/demo_trainer.cc:1`` (C++ training with no
Python graph build).  The shared library (``paddle_capi.cpp``) embeds a
CPython runtime and drives the jit-compiling Executor through
``_host.py``; native programs include ``paddle_capi.h`` and link
``-lpaddle_tpu_capi -lpython3.x``.  Two demo programs
(``demo/demo_predictor.cc``, ``demo/demo_trainer.cc``) are the
reference demos' analogs and are built+run by ``tests/test_capi.py``.

Build helpers here compile the library/demos on demand with g++
(same pattern as recordio's compile-on-first-use; no pybind11 — the
CPython C API is the binding layer).
"""

import os
import subprocess
import sysconfig
import tempfile

__all__ = ["lib_path", "build_lib", "build_demo", "header_path",
           "native_available"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "paddle_capi.cpp")
_HDR = os.path.join(_HERE, "paddle_capi.h")
_LIB_PATH = os.path.join(_HERE, "_libpaddle_tpu_capi.so")


def header_path():
    return _HDR


def _python_link_flags():
    """-I/-L/-l flags to embed this interpreter (python3-config --embed
    equivalent, resolved from sysconfig so the venv's base is used)."""
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    return ["-I" + inc], ["-L" + libdir, "-lpython" + ver,
                          "-Wl,-rpath," + libdir, "-ldl", "-lm"]


def build_lib(force=False):
    """Compile the shared library; returns its path."""
    src_mtime = max(os.path.getmtime(_SRC), os.path.getmtime(_HDR))
    if not force and os.path.exists(_LIB_PATH) and \
            os.path.getmtime(_LIB_PATH) >= src_mtime:
        return _LIB_PATH
    cflags, ldflags = _python_link_flags()
    fd, tmp = tempfile.mkstemp(dir=_HERE, prefix="_libcapi_", suffix=".so")
    os.close(fd)
    try:
        cmd = (["g++", "-O2", "-shared", "-fPIC", "-std=c++17"] + cflags +
               [_SRC, "-o", tmp] + ldflags)
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _LIB_PATH)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return _LIB_PATH


def lib_path():
    return build_lib()


def build_demo(name, out_path=None):
    """Compile ``demo/<name>.cc`` against the library; returns the
    binary path."""
    lib = build_lib()
    src = os.path.join(_HERE, "demo", name + ".cc")
    out = out_path or os.path.join(tempfile.gettempdir(),
                                   "pd_" + name + "_%d" % os.getpid())
    cflags, ldflags = _python_link_flags()
    cmd = (["g++", "-O2", "-std=c++17", "-I" + _HERE] + cflags +
           [src, lib, "-Wl,-rpath," + _HERE, "-o", out] + ldflags)
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return out


def native_available():
    try:
        build_lib()
        return True
    except (OSError, subprocess.CalledProcessError):
        return False
