"""Embedded-runtime host functions for the C ABI.

The native side (``paddle_capi.cpp``) embeds CPython and calls ONLY the
flat functions in this module, marshalling tensors as
``(name, dtype_str, shape_tuple, data_bytes)`` quads — the narrowest
possible boundary, so the C layer needs no numpy/jax knowledge.

Parity map: reference ``paddle/capi/capi.h`` (gradient-machine C ABI for
deployment) + ``paddle/fluid/train/demo/demo_trainer.cc:1`` (train from
a saved ProgramDesc with no Python graph build).  Here the saved JSON
ProgramDesc is the exchange format and the jit-compiled Executor is the
engine the C ABI drives.
"""

import json
import threading

import numpy as np

from .. import inference as _inference
from .. import io as _io
from ..executor import CPUPlace, Executor, TPUPlace
from ..scope import Scope, scope_guard

_lock = threading.Lock()
_handles = {}
_next_id = 1


def _register(obj):
    global _next_id
    with _lock:
        h = _next_id
        _next_id += 1
        _handles[h] = obj
    return h


def _get(h):
    obj = _handles.get(h)
    if obj is None:
        raise KeyError("invalid handle %d" % h)
    return obj


def _release(h):
    with _lock:
        _handles.pop(h, None)


def _decode(feeds):
    out = {}
    for name, dtype, shape, data in feeds or []:
        arr = np.frombuffer(data, dtype=np.dtype(dtype)).reshape(
            tuple(int(s) for s in shape))
        out[name] = arr
    return out


def _encode(name, arr):
    arr = np.ascontiguousarray(np.asarray(arr))
    return (name, str(arr.dtype), tuple(int(s) for s in arr.shape),
            arr.tobytes())


def _place(device):
    return TPUPlace() if device == "tpu" else CPUPlace()


# -- predictor ---------------------------------------------------------------

def predictor_create(model_dir, device="cpu"):
    cfg = _inference.NativeConfig(model_dir=model_dir,
                                  use_gpu=(device == "tpu"))
    return _register(_inference.create_paddle_predictor(cfg))


def predictor_io_json(h):
    """JSON of feed/fetch metadata so a C driver can synthesize inputs
    without knowing the model."""
    p = _get(h)
    blk = p._program.global_block()
    feeds = []
    for n in p.feed_names:
        v = blk.var(n)
        feeds.append({"name": n,
                      "shape": [int(s) if s and s > 0 else -1
                                for s in (v.shape or [])],
                      "dtype": str(np.dtype(v.dtype or "float32")),
                      "lod_level": int(v.lod_level or 0)})
    return json.dumps({"feeds": feeds, "fetches": p.fetch_names})


def predictor_run(h, feeds):
    p = _get(h)
    feed = _decode(feeds)
    outs = p.run(feed)
    return [_encode(t.name, t.data) for t in outs]


def predictor_destroy(h):
    _release(h)


# -- trainer (train-from-saved-program) --------------------------------------

class _Trainer:
    def __init__(self, model_dir, params_dir=None, device="cpu"):
        self.main, self.startup, self.loss_name, self.feed_names = \
            _io.load_train_program(model_dir)
        self.scope = Scope()
        self.exe = Executor(_place(device))
        if params_dir:
            with scope_guard(self.scope):
                _io.load_persistables(self.exe, params_dir, self.main)
        else:
            self.exe.run(self.startup, scope=self.scope)
        self.rng = np.random.RandomState(0)

    def synth_feed(self, batch_size):
        feed = {}
        blk = self.main.global_block()
        for name in self.feed_names:
            v = blk.var(name)
            shape = [batch_size if (s is None or s < 0) else s
                     for s in (v.shape or (1,))]
            dtype = str(np.dtype(v.dtype or "float32"))
            if "int" in dtype:
                feed[name] = self.rng.randint(0, 2, shape).astype(dtype)
            else:
                feed[name] = self.rng.standard_normal(shape).astype(dtype)
            if (v.lod_level or 0) >= 1:
                feed[name + "@LEN"] = np.full((shape[0],), shape[1],
                                              "int32")
        return feed

    def step(self, feed):
        loss, = self.exe.run(self.main, feed=feed,
                             fetch_list=[self.loss_name],
                             scope=self.scope)
        return float(np.asarray(loss).reshape(-1)[0])


def trainer_create(model_dir, params_dir="", device="cpu"):
    return _register(_Trainer(model_dir, params_dir or None, device))


def trainer_step(h, feeds):
    t = _get(h)
    return t.step(_decode(feeds))


def trainer_step_synth(h, batch_size):
    t = _get(h)
    return t.step(t.synth_feed(int(batch_size)))


def trainer_save(h, dirname):
    t = _get(h)
    with scope_guard(t.scope):
        _io.save_persistables(t.exe, dirname, t.main)


def trainer_destroy(h):
    _release(h)
