/* paddle_tpu C ABI — native deployment + train-from-saved-program.
 *
 * Parity: reference paddle/capi/capi.h (C inference ABI for
 * embedded/mobile deployment) and paddle/fluid/train/demo/
 * demo_trainer.cc:1 (train from serialized ProgramDescs with no Python
 * graph build).  TPU-first redesign: the engine behind this ABI is the
 * jit-compiling Executor; the library embeds a CPython runtime the way
 * the reference's PyDataProvider2 embedded one inside the C++ trainer
 * — the native surface is real, the compute path is XLA.
 *
 * Thread-safety: calls may come from any thread; the implementation
 * takes the GIL per call.  When loaded INTO an existing Python process
 * (e.g. via ctypes for testing) pd_init detects the live interpreter
 * and becomes a no-op.
 */
#ifndef PADDLE_TPU_CAPI_H
#define PADDLE_TPU_CAPI_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef enum {
  PD_FLOAT32 = 0,
  PD_FLOAT64 = 1,
  PD_INT32 = 2,
  PD_INT64 = 3,
} pd_dtype;

/* Row-major dense tensor crossing the ABI.  For inputs, all pointers are
 * caller-owned.  For outputs, the library allocates name/shape/data;
 * release with pd_tensor_release. */
typedef struct {
  char* name;
  pd_dtype dtype;
  int64_t* shape;
  int32_t rank;
  void* data;
  int64_t data_size; /* bytes */
} pd_tensor;

/* Start the embedded runtime.  python_exe: path of the (venv) python
 * whose site-packages hold paddle_tpu, e.g. "/opt/venv/bin/python3";
 * NULL uses the PD_PYTHON env var, else "python3".  Returns 0 on
 * success.  No-op (returns 0) inside a live Python process. */
int pd_init(const char* python_exe);

/* Last error message of this thread's most recent failed call. */
const char* pd_last_error(void);

/* ---- inference (reference capi gradient-machine ABI) ---- */

typedef struct pd_predictor pd_predictor;

/* model_dir: directory written by fluid io.save_inference_model.
 * device: "cpu" or "tpu".  NULL on failure (see pd_last_error). */
pd_predictor* pd_predictor_create(const char* model_dir,
                                  const char* device);

/* malloc'd JSON {"feeds":[{name,shape,dtype,lod_level}...],
 * "fetches":[...]}; caller frees with pd_free. */
char* pd_predictor_io_json(pd_predictor* p);

/* Run inference: n_out gets the number of outputs written to *outs
 * (library-allocated array; release each tensor with
 * pd_tensor_release then the array with pd_free).  Returns 0 on
 * success. */
int pd_predictor_run(pd_predictor* p, const pd_tensor* ins, int32_t n_in,
                     pd_tensor** outs, int32_t* n_out);

void pd_predictor_destroy(pd_predictor* p);

/* ---- trainer (reference train/demo/demo_trainer.cc capability) ---- */

typedef struct pd_trainer pd_trainer;

/* model_dir: directory written by io.save_train_program (full forward+
 * backward+optimizer program).  params_dir: restore persistables from a
 * save_persistables dir instead of running the startup program; may be
 * NULL/"".  device: "cpu" or "tpu". */
pd_trainer* pd_trainer_create(const char* model_dir,
                              const char* params_dir,
                              const char* device);

/* One training step on caller-provided feeds; *loss gets the fetched
 * loss scalar.  Returns 0 on success. */
int pd_trainer_step(pd_trainer* t, const pd_tensor* ins, int32_t n_in,
                    double* loss);

/* One training step on synthesized feeds derived from the program's
 * data vars (the demo path; reference demo_trainer fabricates its
 * input the same way). */
int pd_trainer_step_synth(pd_trainer* t, int32_t batch_size,
                          double* loss);

/* Save persistables (params + optimizer state) to dirname. */
int pd_trainer_save(pd_trainer* t, const char* dirname);

void pd_trainer_destroy(pd_trainer* t);

/* ---- memory ---- */

void pd_tensor_release(pd_tensor* t); /* frees members, not t itself */
void pd_free(void* p);

#ifdef __cplusplus
}
#endif
#endif /* PADDLE_TPU_CAPI_H */
